# R inference binding (reference r/example/mobilenet.r drives the Python
# API through reticulate; same approach here over paddle_tpu.inference).
#
#   source("r/paddle_infer.R")
#   predictor <- pd_create_predictor("path/to/model_prefix")
#   out <- pd_run(predictor, array(runif(1*3*224*224), c(1, 3, 224, 224)))

library(reticulate)

pd_create_predictor <- function(model_prefix) {
  inference <- import("paddle_tpu.inference")
  config <- inference$Config(model_prefix)
  inference$create_predictor(config)
}

pd_run <- function(predictor, x) {
  np <- import("numpy")
  arr <- np$asarray(x, dtype = "float32")
  outs <- predictor$run(list(arr))
  lapply(outs, function(o) py_to_r(np$asarray(o)))
}
