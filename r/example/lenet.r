# LeNet inference from R (reference r/example/mobilenet.r).
# Save a model first, e.g. in Python:
#   import paddle_tpu as paddle
#   from paddle_tpu.vision.models import LeNet
#   from paddle_tpu.static import InputSpec
#   paddle.jit.save(LeNet(), "/tmp/lenet",
#                   input_spec=[InputSpec([1, 1, 28, 28], "float32", "x")])

source(file.path(dirname(sys.frame(1)$ofile), "..", "paddle_infer.R"))

predictor <- pd_create_predictor("/tmp/lenet")
img <- array(runif(1 * 1 * 28 * 28), c(1, 1, 28, 28))
logits <- pd_run(predictor, img)[[1]]
cat("predicted class:", which.max(logits) - 1, "\n")
