// Predictor: cgo binding over the C inference API (reference
// go/paddle/predictor.go wraps paddle_c_api.h the same way).
//
// Build (the shared library embeds CPython, so link python too):
//
//	CGO_CFLAGS="-I${REPO}/csrc" \
//	CGO_LDFLAGS="-L${REPO}/csrc -lpd_infer_capi -lpython3.12" \
//	go build ./...
package paddle

/*
#include <stdlib.h>
#include "pd_c_api.h"
*/
import "C"

import (
	"errors"
	"runtime"
	"unsafe"
)

// Predictor wraps the opaque PD_Predictor handle.
type Predictor struct {
	handle *C.PD_Predictor
}

// NewPredictor creates a predictor from the config's model prefix.
func NewPredictor(cfg *Config) (*Predictor, error) {
	cs := C.CString(cfg.Model())
	defer C.free(unsafe.Pointer(cs))
	h := C.PD_NewPredictor(cs)
	if h == nil {
		return nil, errors.New(C.GoString(C.PD_GetLastError()))
	}
	p := &Predictor{handle: h}
	runtime.SetFinalizer(p, (*Predictor).Delete)
	return p, nil
}

// Run executes the model on one input tensor and returns the first output.
func (p *Predictor) Run(input *Tensor) (*Tensor, error) {
	if p.handle == nil {
		return nil, errors.New("predictor already deleted")
	}
	var outData *C.float
	var outShape [8]C.int64_t
	var outNdim C.int
	rc := C.PD_PredictorRun(
		p.handle,
		(*C.float)(unsafe.Pointer(&input.Data[0])),
		(*C.int64_t)(unsafe.Pointer(&input.Shape[0])),
		C.int(len(input.Shape)),
		&outData, &outShape[0], &outNdim)
	if rc != 0 {
		return nil, errors.New(C.GoString(C.PD_GetLastError()))
	}
	defer C.PD_FreeBuffer(unsafe.Pointer(outData))
	shape := make([]int64, int(outNdim))
	n := int64(1)
	for i := range shape {
		shape[i] = int64(outShape[i])
		n *= shape[i]
	}
	data := make([]float32, n)
	src := unsafe.Slice((*float32)(unsafe.Pointer(outData)), n)
	copy(data, src)
	return &Tensor{Shape: shape, Data: data}, nil
}

// Delete releases the native predictor. Safe to call twice.
func (p *Predictor) Delete() {
	if p.handle != nil {
		C.PD_DeletePredictor(p.handle)
		p.handle = nil
	}
}
