// Tensor type for the Go binding (reference go/paddle/tensor.go).
package paddle

import "fmt"

// Tensor is a dense float32 tensor in row-major order.
type Tensor struct {
	Shape []int64
	Data  []float32
}

// NewTensor builds a tensor and validates that len(data) matches shape.
func NewTensor(shape []int64, data []float32) (*Tensor, error) {
	n := int64(1)
	for _, d := range shape {
		n *= d
	}
	if int64(len(data)) != n {
		return nil, fmt.Errorf("tensor data length %d != shape volume %d",
			len(data), n)
	}
	return &Tensor{Shape: shape, Data: data}, nil
}

// Numel returns the element count.
func (t *Tensor) Numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}
