// Package paddle is the Go inference binding (reference
// go/paddle/config.go — cgo over paddle_c_api.h; here over csrc/pd_c_api.h
// backed by the XLA predictor).
package paddle

// Config holds predictor creation options. The reference exposes dozens of
// AnalysisConfig knobs (GPU memory, IR passes, TensorRT); on TPU the XLA
// runtime owns those decisions, so the surface is the model location.
type Config struct {
	modelPrefix string
}

// SetModel points the config at a saved model ({prefix}.pdmodel +
// {prefix}.pdiparams, written by jit.save / save_inference_model).
func (c *Config) SetModel(prefix string) {
	c.modelPrefix = prefix
}

// Model returns the configured model prefix.
func (c *Config) Model() string {
	return c.modelPrefix
}
