// C inference API header (reference paddle/fluid/inference/capi/
// paddle_c_api.h). Implemented by inference_capi.cc; consumed by ctypes
// (tests/test_capi.py), the Go binding (go/paddle/) and any C caller.
#ifndef PD_C_API_H_
#define PD_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Predictor PD_Predictor;

// Last error message from any failed call (never NULL).
const char* PD_GetLastError();

// Create a predictor from a saved model prefix ({prefix}.pdmodel /
// {prefix}.pdiparams, as written by jit.save). NULL on failure.
PD_Predictor* PD_NewPredictor(const char* model_prefix);

// Run with one float32 input tensor. *out_data is malloc'd (free with
// PD_FreeBuffer); out_shape must hold 8 dims. Returns 0 on success.
int PD_PredictorRun(PD_Predictor* pred, const float* input,
                    const int64_t* shape, int ndim, float** out_data,
                    int64_t* out_shape, int* out_ndim);

void PD_FreeBuffer(void* p);

void PD_DeletePredictor(PD_Predictor* pred);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // PD_C_API_H_
