// C inference API (reference paddle/fluid/inference/capi/c_api.cc:
// PD_NewPredictor / PD_PredictorRun / PD_DeletePredictor over PD_Tensor).
//
// TPU redesign: the reference's C API fronts a C++ AnalysisPredictor; here
// the predictor IS the XLA runtime reached through an embedded CPython
// (the StableHLO artifact compiles/executes inside jax). The C surface
// matches the reference's shape: opaque predictor handle, run with raw
// float32 buffers + shapes, outputs malloc'd for the caller,
// PD_GetLastError for diagnostics. Single-threaded contract (one GIL
// owner), float32 tensors; build: `make libpd_infer_capi.so`.
#include <Python.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>

static std::string g_err;

static void set_err_from_python() {
  PyObject *t = nullptr, *v = nullptr, *tb = nullptr;
  PyErr_Fetch(&t, &v, &tb);
  PyObject* s = v ? PyObject_Str(v) : nullptr;
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  g_err = c ? c : "unknown python error";
  Py_XDECREF(s);
  Py_XDECREF(t);
  Py_XDECREF(v);
  Py_XDECREF(tb);
}

extern "C" {

struct PD_Predictor {
  PyObject* pred;
};

const char* PD_GetLastError() { return g_err.c_str(); }

// honor JAX_PLATFORMS even though this image's sitecustomize pre-imports
// jax (same workaround as bench.py)
static bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    int rc = PyRun_SimpleString(
        "import os\n"
        "import jax\n"
        "_p = os.environ.get('JAX_PLATFORMS')\n"
        "if _p:\n"
        "    jax.config.update('jax_platforms', _p)\n");
    if (rc != 0) {
      g_err = "failed to initialize jax platform config";
      return false;
    }
  }
  return true;
}

PD_Predictor* PD_NewPredictor(const char* model_prefix) {
  if (!ensure_python()) return nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (!mod) {
    set_err_from_python();
    return nullptr;
  }
  PyObject* cfg_cls = PyObject_GetAttrString(mod, "Config");
  PyObject* cfg =
      cfg_cls ? PyObject_CallFunction(cfg_cls, "s", model_prefix) : nullptr;
  PyObject* mk =
      cfg ? PyObject_GetAttrString(mod, "create_predictor") : nullptr;
  PyObject* pred = mk ? PyObject_CallFunctionObjArgs(mk, cfg, nullptr)
                      : nullptr;
  Py_XDECREF(mk);
  Py_XDECREF(cfg);
  Py_XDECREF(cfg_cls);
  Py_DECREF(mod);
  if (!pred) {
    set_err_from_python();
    return nullptr;
  }
  PD_Predictor* h = new PD_Predictor();
  h->pred = pred;
  return h;
}

// Run with one float32 input; outputs the first result tensor.
// out_data is malloc'd (caller frees via PD_FreeBuffer); out_shape must
// hold up to 8 dims; returns 0 on success.
int PD_PredictorRun(PD_Predictor* h, const float* input,
                    const int64_t* shape, int ndim, float** out_data,
                    int64_t* out_shape, int* out_ndim) {
  if (!h || !h->pred) {
    g_err = "null predictor";
    return 1;
  }
  int64_t total = 1;
  for (int i = 0; i < ndim; ++i) total *= shape[i];

  PyObject* np = PyImport_ImportModule("numpy");
  if (!np) {
    set_err_from_python();
    return 2;
  }
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(input)),
      total * static_cast<int64_t>(sizeof(float)), PyBUF_READ);
  PyObject* flat =
      mv ? PyObject_CallMethod(np, "frombuffer", "Os", mv, "float32")
         : nullptr;
  PyObject* pyshape = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SetItem(pyshape, i, PyLong_FromLongLong(shape[i]));
  PyObject* arr =
      flat ? PyObject_CallMethod(flat, "reshape", "O", pyshape) : nullptr;
  PyObject* out_list =
      arr ? PyObject_CallMethod(h->pred, "run", "[O]", arr) : nullptr;
  int rc = 0;
  if (!out_list || !PyList_Check(out_list) || PyList_Size(out_list) < 1) {
    set_err_from_python();
    rc = 3;
  } else {
    PyObject* out0 = PyList_GetItem(out_list, 0);  // borrowed
    PyObject* cont =
        PyObject_CallMethod(np, "ascontiguousarray", "Os", out0, "float32");
    PyObject* bytes =
        cont ? PyObject_CallMethod(cont, "tobytes", nullptr) : nullptr;
    PyObject* oshape =
        cont ? PyObject_GetAttrString(cont, "shape") : nullptr;
    if (!bytes || !oshape) {
      set_err_from_python();
      rc = 4;
    } else if (PyTuple_Size(oshape) > 8) {
      g_err = "output rank > 8 unsupported by the C API";
      rc = 5;
    } else {
      char* buf;
      Py_ssize_t blen;
      PyBytes_AsStringAndSize(bytes, &buf, &blen);
      *out_data = static_cast<float*>(malloc(blen));
      memcpy(*out_data, buf, blen);
      *out_ndim = static_cast<int>(PyTuple_Size(oshape));
      for (int i = 0; i < *out_ndim; ++i)
        out_shape[i] = PyLong_AsLongLong(PyTuple_GetItem(oshape, i));
    }
    Py_XDECREF(oshape);
    Py_XDECREF(bytes);
    Py_XDECREF(cont);
  }
  Py_XDECREF(out_list);
  Py_XDECREF(arr);
  Py_XDECREF(pyshape);
  Py_XDECREF(flat);
  Py_XDECREF(mv);
  Py_DECREF(np);
  return rc;
}

void PD_FreeBuffer(void* p) { free(p); }

void PD_DeletePredictor(PD_Predictor* h) {
  if (h) {
    Py_XDECREF(h->pred);
    delete h;
  }
}

}  // extern "C"
