// Multi-slot data feed parser (C++, ctypes ABI).
//
// Reference: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed:664 —
// the industrial text format "slot_num (slot_size id...|val...)*" parsed
// off the training thread. Fresh implementation: a multi-threaded text
// parser that converts slot files to packed int64/float32 buffers the
// Python Dataset hands to the device as whole batches.
//
// Line format (same contract as the reference's MultiSlotDataGenerator
// output):  <n_0> v ... v <n_1> v ... v ...   for a fixed slot schema,
// where each slot is either int64 (sparse ids) or float32 (dense).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct ParsedFile {
  // per slot: concatenated values + per-line lengths (LoD offsets)
  std::vector<std::vector<int64_t>> int_vals;
  std::vector<std::vector<float>> float_vals;
  std::vector<std::vector<int64_t>> lengths;  // per slot per line
  int64_t n_lines = 0;
};

// schema: for each slot, 0 = int64, 1 = float32
ParsedFile* parse(const char* path, const int* schema, int n_slots) {
  FILE* f = std::fopen(path, "r");
  if (!f) return nullptr;
  auto* out = new ParsedFile();
  out->int_vals.resize(n_slots);
  out->float_vals.resize(n_slots);
  out->lengths.resize(n_slots);

  char* line = nullptr;
  size_t cap = 0;
  ssize_t len;
  while ((len = getline(&line, &cap, f)) > 0) {
    char* p = line;
    bool ok = true;
    for (int s = 0; s < n_slots && ok; ++s) {
      char* end;
      long n = strtol(p, &end, 10);
      if (end == p) { ok = false; break; }
      p = end;
      out->lengths[s].push_back(n);
      for (long i = 0; i < n; ++i) {
        if (schema[s] == 0) {
          long long v = strtoll(p, &end, 10);
          if (end == p) { ok = false; break; }
          out->int_vals[s].push_back((int64_t)v);
        } else {
          float v = strtof(p, &end);
          if (end == p) { ok = false; break; }
          out->float_vals[s].push_back(v);
        }
        p = end;
      }
    }
    if (ok) out->n_lines++;
  }
  free(line);
  std::fclose(f);
  return out;
}

}  // namespace

extern "C" {

void* data_feed_parse(const char* path, const int* schema, int n_slots) {
  return parse(path, schema, n_slots);
}

int64_t data_feed_n_lines(void* h) { return ((ParsedFile*)h)->n_lines; }

int64_t data_feed_slot_size(void* h, int slot, int is_float) {
  auto* p = (ParsedFile*)h;
  return is_float ? (int64_t)p->float_vals[slot].size()
                  : (int64_t)p->int_vals[slot].size();
}

void data_feed_copy_int(void* h, int slot, int64_t* out) {
  auto& v = ((ParsedFile*)h)->int_vals[slot];
  std::memcpy(out, v.data(), v.size() * sizeof(int64_t));
}

void data_feed_copy_float(void* h, int slot, float* out) {
  auto& v = ((ParsedFile*)h)->float_vals[slot];
  std::memcpy(out, v.data(), v.size() * sizeof(float));
}

void data_feed_copy_lengths(void* h, int slot, int64_t* out) {
  auto& v = ((ParsedFile*)h)->lengths[slot];
  std::memcpy(out, v.data(), v.size() * sizeof(int64_t));
}

void data_feed_destroy(void* h) { delete (ParsedFile*)h; }

}  // extern "C"
