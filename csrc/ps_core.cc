// Parameter-server table core (C++, ctypes ABI).
//
// Reference: paddle/fluid/distributed/table/common_dense_table.cc and
// common_sparse_table.cc — dense parameter arrays and a sharded hash
// sparse-embedding table with the optimizer rule applied server-side.
// This is a fresh implementation for the TPU framework: same capability
// (pull/push with sgd/adam/sum rules, init-on-miss, save/load), no brpc —
// transport lives in Python; the hot row math and the hash sharding are
// native here.
//
// Build: make -C csrc   (produces libps_core.so; loaded via ctypes)

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

enum class Rule { kSum, kSGD, kAdam };

Rule parse_rule(const char* r) {
  if (!r) return Rule::kSGD;
  std::string s(r);
  if (s == "adam") return Rule::kAdam;
  if (s == "sum") return Rule::kSum;
  return Rule::kSGD;
}

struct AdamState {
  std::vector<float> m1, m2;
  int64_t step = 0;
};

struct DenseTable {
  std::vector<float> data;
  AdamState adam;
  Rule rule;
  float lr;
  std::mutex mu;

  DenseTable(int64_t size, Rule r, float lr_) : data(size, 0.f), rule(r),
                                                lr(lr_) {
    if (rule == Rule::kAdam) {
      adam.m1.assign(size, 0.f);
      adam.m2.assign(size, 0.f);
    }
  }

  void push(const float* grad, int64_t n) {
    std::lock_guard<std::mutex> g(mu);
    n = std::min<int64_t>(n, data.size());
    switch (rule) {
      case Rule::kSum:
        for (int64_t i = 0; i < n; ++i) data[i] += grad[i];
        break;
      case Rule::kSGD:
        for (int64_t i = 0; i < n; ++i) data[i] -= lr * grad[i];
        break;
      case Rule::kAdam: {
        adam.step++;
        const float b1 = 0.9f, b2 = 0.999f, eps = 1e-8f;
        const float c1 = 1.f - std::pow(b1, (float)adam.step);
        const float c2 = 1.f - std::pow(b2, (float)adam.step);
        for (int64_t i = 0; i < n; ++i) {
          adam.m1[i] = b1 * adam.m1[i] + (1 - b1) * grad[i];
          adam.m2[i] = b2 * adam.m2[i] + (1 - b2) * grad[i] * grad[i];
          data[i] -= lr * (adam.m1[i] / c1) /
                     (std::sqrt(adam.m2[i] / c2) + eps);
        }
        break;
      }
    }
  }
};

struct SparseRow {
  std::vector<float> w;
  std::vector<float> m1, m2;  // adam moments (lazily sized)
  int64_t step = 0;
};

// Sharded hash table: 16 shards, per-shard lock (reference
// common_sparse_table bucketing).
struct SparseTable {
  static constexpr int kShards = 16;
  int64_t dim;
  Rule rule;
  float lr;
  float init_range;
  std::mt19937 seed_gen;
  std::unordered_map<int64_t, SparseRow> shards[kShards];
  std::mutex mus[kShards];

  SparseTable(int64_t d, Rule r, float lr_, float ir, uint32_t seed)
      : dim(d), rule(r), lr(lr_), init_range(ir), seed_gen(seed) {}

  int shard_of(int64_t id) const {
    return (int)(((uint64_t)id * 0x9E3779B97F4A7C15ull) >> 60) & (kShards - 1);
  }

  SparseRow& row(int64_t id) {
    int s = shard_of(id);
    auto it = shards[s].find(id);
    if (it == shards[s].end()) {
      SparseRow r;
      r.w.resize(dim);
      // deterministic per-id init (uniform in [-init_range, init_range])
      std::mt19937 gen((uint32_t)(id * 2654435761u) ^ seed_gen());
      std::uniform_real_distribution<float> dist(-init_range, init_range);
      std::mt19937 gen2((uint32_t)(id * 2654435761u));
      for (int64_t i = 0; i < dim; ++i) r.w[i] = dist(gen2);
      it = shards[s].emplace(id, std::move(r)).first;
    }
    return it->second;
  }

  void pull(const int64_t* ids, int64_t n, float* out) {
    for (int64_t i = 0; i < n; ++i) {
      int s = shard_of(ids[i]);
      std::lock_guard<std::mutex> g(mus[s]);
      SparseRow& r = row(ids[i]);
      std::memcpy(out + i * dim, r.w.data(), dim * sizeof(float));
    }
  }

  void push(const int64_t* ids, int64_t n, const float* grads) {
    const float b1 = 0.9f, b2 = 0.999f, eps = 1e-8f;
    for (int64_t i = 0; i < n; ++i) {
      int s = shard_of(ids[i]);
      std::lock_guard<std::mutex> g(mus[s]);
      SparseRow& r = row(ids[i]);
      const float* gr = grads + i * dim;
      switch (rule) {
        case Rule::kSum:
          for (int64_t j = 0; j < dim; ++j) r.w[j] += gr[j];
          break;
        case Rule::kSGD:
          for (int64_t j = 0; j < dim; ++j) r.w[j] -= lr * gr[j];
          break;
        case Rule::kAdam: {
          if (r.m1.empty()) {
            r.m1.assign(dim, 0.f);
            r.m2.assign(dim, 0.f);
          }
          r.step++;
          const float c1 = 1.f - std::pow(b1, (float)r.step);
          const float c2 = 1.f - std::pow(b2, (float)r.step);
          for (int64_t j = 0; j < dim; ++j) {
            r.m1[j] = b1 * r.m1[j] + (1 - b1) * gr[j];
            r.m2[j] = b2 * r.m2[j] + (1 - b2) * gr[j] * gr[j];
            r.w[j] -= lr * (r.m1[j] / c1) / (std::sqrt(r.m2[j] / c2) + eps);
          }
          break;
        }
      }
    }
  }

  int64_t size() const {
    int64_t n = 0;
    for (int s = 0; s < kShards; ++s) n += shards[s].size();
    return n;
  }

  int64_t save(const char* path) {
    FILE* f = std::fopen(path, "wb");
    if (!f) return -1;
    int64_t n = size();
    std::fwrite(&n, sizeof(n), 1, f);
    std::fwrite(&dim, sizeof(dim), 1, f);
    for (int s = 0; s < kShards; ++s) {
      for (auto& kv : shards[s]) {
        std::fwrite(&kv.first, sizeof(int64_t), 1, f);
        std::fwrite(kv.second.w.data(), sizeof(float), dim, f);
      }
    }
    std::fclose(f);
    return n;
  }

  int64_t load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    int64_t n = 0, d = 0;
    if (std::fread(&n, sizeof(n), 1, f) != 1 ||
        std::fread(&d, sizeof(d), 1, f) != 1 || d != dim) {
      std::fclose(f);
      return -1;
    }
    for (int64_t i = 0; i < n; ++i) {
      int64_t id;
      if (std::fread(&id, sizeof(id), 1, f) != 1) break;
      SparseRow r;
      r.w.resize(dim);
      if (std::fread(r.w.data(), sizeof(float), dim, f) != (size_t)dim)
        break;
      int s = shard_of(id);
      shards[s][id] = std::move(r);
    }
    std::fclose(f);
    return n;
  }
};

}  // namespace

extern "C" {

void* dense_table_create(int64_t size, const char* rule, float lr) {
  return new DenseTable(size, parse_rule(rule), lr);
}

void dense_table_destroy(void* t) { delete (DenseTable*)t; }

void dense_table_pull(void* t, float* out, int64_t n) {
  auto* dt = (DenseTable*)t;
  std::lock_guard<std::mutex> g(dt->mu);
  std::memcpy(out, dt->data.data(),
              std::min<int64_t>(n, dt->data.size()) * sizeof(float));
}

void dense_table_push(void* t, const float* grad, int64_t n) {
  ((DenseTable*)t)->push(grad, n);
}

void dense_table_set(void* t, const float* vals, int64_t n) {
  auto* dt = (DenseTable*)t;
  std::lock_guard<std::mutex> g(dt->mu);
  std::memcpy(dt->data.data(), vals,
              std::min<int64_t>(n, dt->data.size()) * sizeof(float));
}

void* sparse_table_create(int64_t dim, const char* rule, float lr,
                          float init_range, uint32_t seed) {
  return new SparseTable(dim, parse_rule(rule), lr, init_range, seed);
}

void sparse_table_destroy(void* t) { delete (SparseTable*)t; }

void sparse_table_pull(void* t, const int64_t* ids, int64_t n, float* out) {
  ((SparseTable*)t)->pull(ids, n, out);
}

void sparse_table_push(void* t, const int64_t* ids, int64_t n,
                       const float* grads) {
  ((SparseTable*)t)->push(ids, n, grads);
}

int64_t sparse_table_size(void* t) { return ((SparseTable*)t)->size(); }

int64_t sparse_table_save(void* t, const char* path) {
  return ((SparseTable*)t)->save(path);
}

int64_t sparse_table_load(void* t, const char* path) {
  return ((SparseTable*)t)->load(path);
}

}  // extern "C"
