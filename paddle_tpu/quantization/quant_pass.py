"""Static-Program quantization passes (reference
`fluid/contrib/slim/quantization/quantization_pass.py`:
QuantizationTransformPass inserts fake_quant/dequant ops for QAT;
QuantizationFreezePass rewrites the trained program to int8 weights).

TPU redesign over the op-level Program IR: the transform pass WRAPS each
quantizable op's computation with fake-quant on its inputs (straight-
through estimator — jax.grad differentiates the wrapped fn directly, no
separate grad ops needed); the freeze pass bakes the WEIGHT (the ≥2-D
parameter input) in as an int8 constant with per-output-channel scales,
drops it from the program's parameter table, and dequantizes inside the
op body.

Scope note: only block-0 ops are rewritten — ops recorded inside
cond/while sub-blocks execute through the parent op's fused closure,
which a Program-level pass cannot reach (a warning is emitted).
"""
from __future__ import annotations

import warnings
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "fake_quant_array"]

_DEFAULT_TYPES = ("matmul", "mul", "linear", "conv2d")

def _pre_quant_store(program) -> Dict[int, object]:
    """Per-program stash of pre-QAT fns keyed by id(op) (the _Op slots
    class can't carry attributes and fns must stay out of the
    json-serializable attrs). Living on the Program ties the lifetime to
    it — a module-global would leak closures and risk id-reuse handing a
    dead program's fn to a new op."""
    store = getattr(program, "_pre_quant_fns", None)
    if store is None:
        store = program._pre_quant_fns = {}
    return store


def fake_quant_array(v, bits):
    """abs-max symmetric fake-quant with straight-through gradient on a
    raw array (shared by fake_quantize_dequantize and the QAT pass)."""
    import jax
    import jax.numpy as jnp
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-8) / qmax
    q = jnp.clip(jnp.round(v / scale), -qmax, qmax)
    return v + jax.lax.stop_gradient(q * scale - v)


def _bump(program):
    """Invalidate Executor jit caches: their key includes the program
    version (static/program.py), which every rewriting pass must bump."""
    program._version = getattr(program, "_version", 0) + 1


def _warn_sub_blocks(program, pass_name):
    if getattr(program, "num_blocks", 1) > 1:
        warnings.warn(
            f"{pass_name}: ops inside cond/while sub-blocks execute "
            "through their parent op's fused closure and are NOT "
            "quantized")


class QuantizationTransformPass:
    """Wrap quantizable ops with fake-quant on every floating input
    (QAT; reference QuantizationTransformPass inserts
    fake_quantize_abs_max + fake_dequantize ops around each). Parameter
    inputs quantize at weight_bits, everything else at activation_bits.
    """

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 quantizable_op_types: Sequence[str] = _DEFAULT_TYPES):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = tuple(quantizable_op_types)

    def apply(self, program):
        import jax.numpy as jnp

        _warn_sub_blocks(program, "QuantizationTransformPass")
        param_slots = {v.slot for v in program.param_vars.values()}
        store = _pre_quant_store(program)
        for op in program.ops:
            if op.name not in self.types or op.attrs.get("quant"):
                continue
            # args align 1:1 with in_refs (the lowering feeds them in
            # order), so per-arg bit widths can be fixed at wrap time
            arg_bits = [self.weight_bits if tag == "s" and ref in
                        param_slots else self.activation_bits
                        for tag, ref in op.in_refs]
            inner = op.fn
            # keep a handle so the freeze pass can replace (not stack on)
            # the QAT wrapper — the reference freeze removes the
            # fake-quant ops it supersedes
            store[id(op)] = inner

            def wrapped(*args, _inner=inner, _bits=tuple(arg_bits)):
                qargs = [
                    fake_quant_array(a, b)
                    if hasattr(a, "dtype")
                    and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                    else a
                    for a, b in zip(args, _bits)]
                return _inner(*qargs)
            op.fn = wrapped
            op.attrs["quant"] = "fake_abs_max"
            op.attrs["weight_bits"] = self.weight_bits
            op.attrs["activation_bits"] = self.activation_bits
        _bump(program)
        return program


class QuantizationFreezePass:
    """Bake the weight input of quantizable ops in as an int8 constant
    (reference QuantizationFreezePass converts weights and rewires
    dequantize after the op). The weight is the ≥2-D parameter input
    (biases stay f32); per-output-channel symmetric scales; the frozen
    parameter leaves program.param_vars so serialized artifacts carry
    the int8 bytes instead of the f32 tensor."""

    def __init__(self, weight_bits: int = 8,
                 quantizable_op_types: Sequence[str] = _DEFAULT_TYPES):
        self.weight_bits = weight_bits
        self.types = tuple(quantizable_op_types)

    def apply(self, program, scope: Optional[Dict[str, np.ndarray]] = None):
        import jax.numpy as jnp

        from ..static.program import global_scope
        scope = scope if scope is not None else global_scope()
        _warn_sub_blocks(program, "QuantizationFreezePass")
        qmax = 2.0 ** (self.weight_bits - 1) - 1
        param_slots = {v.slot: n for n, v in program.param_vars.items()}

        frozen_slots = []
        for op in program.ops:
            if op.name not in self.types or op.attrs.get("frozen"):
                continue
            w_positions = [
                i for i, (tag, ref) in enumerate(op.in_refs)
                if tag == "s" and ref in param_slots
                and np.asarray(scope[param_slots[ref]]).ndim >= 2]
            if not w_positions:
                continue
            pos = w_positions[0]
            slot = op.in_refs[pos][1]
            name = param_slots[slot]
            w = np.asarray(scope[name], np.float32)
            # per-output-channel scale: conv weights are OIHW (out
            # channel = axis 0); matmul/linear weights put the output
            # features last
            if op.name == "conv2d" and w.ndim == 4:
                axes = (1, 2, 3)
            else:
                axes = tuple(range(w.ndim - 1))
            scale = np.maximum(np.abs(w).max(axis=axes, keepdims=True),
                               1e-8) / qmax
            wq = np.clip(np.round(w / scale), -qmax, qmax).astype(np.int8)

            # replace (don't stack on) the QAT wrapper for the WEIGHT
            # only: re-fake-quanting the dequantized weight on a
            # different per-tensor grid would add rounding error on top
            # of the baked int8 values — but activation fake-quant must
            # survive the freeze (the reference removes only the weight
            # fake_quant ops), else the deployed model computes different
            # activations than the QAT-simulated one the user validated
            pre_qat = _pre_quant_store(program).pop(id(op), None)
            if pre_qat is not None:
                act_bits = int(op.attrs.get("activation_bits", 8))

                def inner(*args, _raw=pre_qat, _wpos=pos,
                          _abits=act_bits):
                    qargs = [
                        a if i == _wpos or not (
                            hasattr(a, "dtype") and jnp.issubdtype(
                                jnp.asarray(a).dtype, jnp.floating))
                        else fake_quant_array(a, _abits)
                        for i, a in enumerate(args)]
                    return _raw(*qargs)
            else:
                inner = op.fn
            if op.attrs.pop("quant", None):
                op.attrs["qat_trained"] = True

            def frozen(*args, _inner=inner, _pos=pos,
                       _scale=jnp.asarray(scale)):
                args = list(args)
                args[_pos] = args[_pos].astype(jnp.float32) * _scale
                return _inner(*args)
            op.fn = frozen
            op.in_refs[pos] = ("c", jnp.asarray(wq))
            op.attrs["frozen"] = "int8"
            op.attrs["weight_bits"] = self.weight_bits
            op.attrs["weight_scale_max"] = float(scale.max())
            frozen_slots.append(slot)

        # drop frozen weights from the parameter table unless another op
        # still reads them — serde then omits the f32 tensor entirely
        still_used = {ref for b in program.blocks for o in b.ops
                      for tag, ref in o.in_refs if tag == "s"}
        for slot in frozen_slots:
            if slot not in still_used and slot in param_slots:
                program.param_vars.pop(param_slots[slot], None)
        _bump(program)
        return program
