"""Static-Program quantization passes (reference
`fluid/contrib/slim/quantization/quantization_pass.py`:
QuantizationTransformPass inserts fake_quant/dequant ops for QAT;
QuantizationFreezePass rewrites the trained program to int8 weights).

TPU redesign over the op-level Program IR: the transform pass WRAPS each
quantizable op's computation with fake-quant on its inputs (straight-
through estimator — jax.grad differentiates the wrapped fn directly, no
separate grad ops needed); the freeze pass bakes weights in as int8
constants with per-output-channel scales and dequantizes in f32 after
the int8 contraction.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

import numpy as np

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass"]

_DEFAULT_TYPES = ("matmul", "mul", "linear", "conv2d")


def _fake_quant(v, bits):
    import jax
    import jax.numpy as jnp
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-8) / qmax
    q = jnp.round(v / scale)
    # straight-through estimator: identity gradient
    return v + jax.lax.stop_gradient(jnp.clip(q, -qmax, qmax) * scale - v)


class QuantizationTransformPass:
    """Wrap quantizable ops with fake-quant on every floating input
    (QAT; reference QuantizationTransformPass inserts
    fake_quantize_abs_max + fake_dequantize ops around each)."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 quantizable_op_types: Sequence[str] = _DEFAULT_TYPES):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = tuple(quantizable_op_types)

    def apply(self, program):
        import jax.numpy as jnp
        for op in program.ops:
            if op.name not in self.types or op.attrs.get("quant"):
                continue
            inner = op.fn
            bits = self.activation_bits

            def wrapped(*args, _inner=inner, _bits=bits):
                qargs = [
                    _fake_quant(a, _bits)
                    if hasattr(a, "dtype")
                    and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                    else a for a in args]
                return _inner(*qargs)
            op.fn = wrapped
            op.attrs["quant"] = "fake_abs_max"
            op.attrs["activation_bits"] = self.activation_bits
        return program


class QuantizationFreezePass:
    """Bake parameter inputs of quantizable ops in as int8 constants
    (reference QuantizationFreezePass converts weights and rewires
    dequantize after the op). Per-output-channel symmetric scales; the
    int8 tensor rides the op as a constant, the fn dequantizes into the
    f32 computation — serving artifacts then carry 1/4 the weight bytes.
    """

    def __init__(self, weight_bits: int = 8,
                 quantizable_op_types: Sequence[str] = _DEFAULT_TYPES):
        self.weight_bits = weight_bits
        self.types = tuple(quantizable_op_types)

    def apply(self, program, scope: Optional[Dict[str, np.ndarray]] = None):
        import jax.numpy as jnp

        from ..static.program import global_scope
        scope = scope if scope is not None else global_scope()
        qmax = 2.0 ** (self.weight_bits - 1) - 1
        param_slots = {v.slot: n for n, v in program.param_vars.items()}

        for op in program.ops:
            if op.name not in self.types or op.attrs.get("frozen"):
                continue
            w_positions = [i for i, (tag, ref) in enumerate(op.in_refs)
                           if tag == "s" and ref in param_slots]
            if not w_positions:
                continue
            pos = w_positions[-1]          # weight is the trailing param
            name = param_slots[op.in_refs[pos][1]]
            w = np.asarray(scope[name], np.float32)
            # per-output-channel scale over the last axis
            axes = tuple(range(w.ndim - 1))
            scale = np.maximum(np.abs(w).max(axis=axes, keepdims=True),
                               1e-8) / qmax
            wq = np.clip(np.round(w / scale), -qmax, qmax).astype(np.int8)

            inner = op.fn

            def frozen(*args, _inner=inner, _pos=pos,
                       _scale=jnp.asarray(scale)):
                args = list(args)
                args[_pos] = args[_pos].astype(jnp.float32) * _scale
                return _inner(*args)
            op.fn = frozen
            op.in_refs[pos] = ("c", jnp.asarray(wq))
            op.attrs["frozen"] = "int8"
            op.attrs["weight_bits"] = self.weight_bits
            op.attrs["weight_scale_max"] = float(scale.max())
        return program
