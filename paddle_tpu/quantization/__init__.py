"""Quantization (reference `fluid/contrib/slim/quantization/`:
QuantizationTransformPass, ImperativeQuantAware; `operators/fake_quantize_op`).

TPU-native: fake-quant (per-tensor abs-max, straight-through estimator)
wrapping Linear/Conv weights+activations — QAT trains int8-simulated in
bf16/f32; XLA folds the quant-dequant pairs at inference compile time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..framework.tensor import Tensor, apply_op

__all__ = ["fake_quantize_dequantize", "QuantizedLinear", "QuantizedConv2D",
           "ImperativeQuantAware", "PTQ"]


def fake_quantize_dequantize(x, bits=8, name=None):
    """abs-max symmetric fake quant with STE (reference
    `fake_quantize_dequantize_moving_average_abs_max` op family).
    Raw-array math shared with the QAT Program pass
    (quant_pass.fake_quant_array)."""
    def impl(v):
        from .quant_pass import fake_quant_array
        return fake_quant_array(v, bits)
    return apply_op("fake_quant_dequant", impl, (x,), {})


class QuantizedLinear(nn.Layer):
    def __init__(self, inner: "nn.Linear", weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = inner
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def forward(self, x):
        from ..nn import functional as F
        xq = fake_quantize_dequantize(x, self.activation_bits)
        wq = fake_quantize_dequantize(self.inner.weight, self.weight_bits)
        return F.linear(xq, wq, self.inner.bias)


class QuantizedConv2D(nn.Layer):
    def __init__(self, inner: "nn.Conv2D", weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = inner
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def forward(self, x):
        from ..nn import functional as F
        xq = fake_quantize_dequantize(x, self.activation_bits)
        wq = fake_quantize_dequantize(self.inner.weight, self.weight_bits)
        return F.conv2d(xq, wq, self.inner.bias, self.inner._stride,
                        self.inner._padding, self.inner._dilation,
                        self.inner._groups, self.inner._data_format)


class ImperativeQuantAware:
    """reference `imperative/qat.py` ImperativeQuantAware.quantize —
    rewrites Linear/Conv2D sublayers in place with fake-quant wrappers."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_layer_type=("Conv2D", "Linear"), **kwargs):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = set(quantizable_layer_type)

    def quantize(self, model: nn.Layer):
        for layer in model.sublayers(include_self=True):
            for name, sub in list(layer._sub_layers.items()):
                if type(sub).__name__ == "Linear" and "Linear" in self.types:
                    layer._sub_layers[name] = QuantizedLinear(
                        sub, self.weight_bits, self.activation_bits)
                elif type(sub).__name__ == "Conv2D" and \
                        "Conv2D" in self.types:
                    layer._sub_layers[name] = QuantizedConv2D(
                        sub, self.weight_bits, self.activation_bits)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit
        jit.save(model, path, input_spec=input_spec)


class PTQ:
    """Post-training quantization: collect abs-max ranges on calibration
    batches, then bake fake-quant with frozen scales."""

    def __init__(self, activation_bits=8, weight_bits=8):
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits

    def quantize(self, model):
        return ImperativeQuantAware(
            self.weight_bits, self.activation_bits).quantize(model)

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit
        jit.save(model, path, input_spec=input_spec)


class WeightOnlyLinear(nn.Layer):
    """Weight-only int8/int4 linear (reference direction:
    `paddle.nn.quant.weight_only_linear` in later versions; the v2.0
    slim toolchain stops at fake-quant).

    TPU rationale: serving memory/HBM-bandwidth is the bottleneck, not
    int math — weights store as int8 (4x smaller) or packed int4 (8x,
    two nibbles per byte — nn/quant.py) + per-output-channel fp scales
    and dequantize into the matmul's bf16/fp32 epilogue, which XLA
    fuses; the integer tensor is the only HBM-resident form. The
    quantized buffers are what `jit.save` exports (as runtime ARGUMENTS
    of the StableHLO artifact, never baked constants XLA could
    dequant-fold back to fp32 — see jit/__init__.py)."""

    def __init__(self, inner: "nn.Linear", bits: int = 8):
        super().__init__()
        from ..nn import quant as nn_quant

        if bits not in (8, 4):
            raise ValueError(f"WeightOnlyLinear supports bits=8 or 4, "
                             f"got {bits}")
        self.weight_bits = bits
        algo = f"weight_only_int{bits}"
        q, scale = nn_quant.weight_quantize(inner.weight, algo)
        self.register_buffer(self._qname, Tensor(jnp.asarray(q)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.asarray(scale, jnp.float32)))
        self.bias = inner.bias
        self._out_features = inner._out_features

    @property
    def _qname(self) -> str:
        return "weight_int8" if self.weight_bits == 8 else "weight_int4"

    @property
    def quant_weight(self) -> Tensor:
        return getattr(self, self._qname)

    def quant_weight_spec(self):
        """jit.save manifest hook: (quant buffer attr, scale attr, bits)
        — any layer exposing this has its quantized tensors exported as
        integer runtime arguments of the serving artifact."""
        return [(self._qname, "weight_scale", self.weight_bits)]

    def quant_decode_leaf(self):
        """(q_int8 [in, out], scale [out]) for the generation engine's
        decode-weight pytree (models/gpt.py): int4 unpacks ONCE to int8
        values here (still 4x smaller than fp32 in HBM), so the jitted
        decode math has a single integer dequant form."""
        from ..nn import quant as nn_quant
        q = self.quant_weight._value
        s = self.weight_scale._value
        if self.weight_bits == 4:
            q = nn_quant.unpack_int4(q, s.shape[-1])
        return (q, s)

    def forward(self, x):
        from ..nn.quant import weight_only_linear
        return weight_only_linear(
            x, self.quant_weight, self.weight_scale, self.bias,
            weight_dtype="int8" if self.weight_bits == 8 else "int4")


def quantize_weights(model: nn.Layer, bits: int = 8,
                     _seen=None) -> nn.Layer:
    """Swap every nn.Linear for WeightOnlyLinear in place (weight-only
    PTQ; bits=8 stores int8, bits=4 stores packed two-nibbles-per-byte
    int4). A Linear shared by several parents (tied heads) is quantized
    ONCE and the single replacement is re-linked everywhere, preserving
    tying; fake-quant wrappers (QuantizedLinear/Conv2D) are left
    intact."""
    if bits not in (8, 4):
        raise NotImplementedError("weight-only quantization supports "
                                  "bits=8 or bits=4")
    seen = _seen if _seen is not None else {}
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, nn.Linear):
            rep = seen.get(id(sub))
            if rep is None:
                rep = seen[id(sub)] = WeightOnlyLinear(sub, bits=bits)
            model._sub_layers[name] = rep
        elif isinstance(sub, (QuantizedLinear, QuantizedConv2D)):
            continue   # fake-quant wrappers own their inner Linear
        elif sub is not None:
            quantize_weights(sub, bits, seen)
    return model


__all__ += ["WeightOnlyLinear", "quantize_weights"]

from .quant_pass import (QuantizationFreezePass,  # noqa: F401,E402
                         QuantizationTransformPass)
