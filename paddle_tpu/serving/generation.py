"""Continuous-batching generation engine over a paged KV cache.

The PR 2/3 engine is one-shot: a request enters a bucket, runs once,
leaves. Autoregressive decode — the dominant production inference
workload — needs **iteration-level scheduling** (Orca) over a
**paged KV cache** (vLLM): requests join the running batch via a
prefill pass, every engine step advances EVERY live sequence by one
token through a single jitted decode program, and sequences leave on
EOS / max-tokens / deadline, freeing their pages the same step.

Shape discipline is what makes this TPU-native: the decode batch is a
FIXED number of slots (`FLAGS_gen_max_slots`) with inactive slots
masked, and prompts pad up to `FLAGS_gen_prefill_buckets`, so XLA
compiles exactly **one decode step** and **one prefill per bucket** —
sequences joining and leaving mid-decode never retrace (the compile
ledger in `stats()` proves it, the same exactness contract as the PR 3
per-(device, bucket) ledgers). K/V lives in `serving.PagedKVCache`
pools; on TPU the Pallas `paged_attention` kernel reads pages in place,
elsewhere a dense gather reference keeps the math bit-anchored to
`GPTModel.generate` (`ops/paged_ops.py`). With
`kv_cache_dtype="int8"` (FLAGS_kv_cache_dtype) the pools store int8
pages + per-(layer, head, page) scale pools — quantize-on-append,
dequantize-on-read, ~4x the concurrent sequences per HBM byte; parity
vs fp32 pages is token-level (different compiled programs). A
`quantize_weights`'d model composes independently: its decode-weight
pytree carries (int8, scale) leaves dequantized in-graph.

**Prefix cache (ISSUE 12, `FLAGS_gen_prefix_cache` /
`prefix_cache=True`)**: full pages of prompt K/V are indexed by a
content-hash block chain (`serving/prefix_cache.py`) over refcounted
pages; a request whose prompt walks a cached chain maps those pages
read-only and prefills ONLY the tail through a per-bucket
`prefill_tail` program (tail queries attend cached pages + their own
in-flight K/V — `ops/paged_ops.paged_prefix_attention`). A full-prompt
match recomputes just its last position, copy-on-write splitting the
page that holds it (int8 mode clones the scale row too) so the shared
original is never written under other readers. Zero-on-free keys on
refcounts — a freed sequence's shared pages survive for future hits —
and refcount-0 cached chains are LRU-evicted BEFORE alloc whenever the
free list alone is short, so `can_admit`/`headroom` count them as
reclaimable. TTFT collapses for shared-system-prompt traffic while
greedy output stays token-identical with the cache off: the cached
pages hold the same K/V the skipped prefill would have produced.

**Speculative decoding (ISSUE 14, `FLAGS_gen_spec_k` / `spec_k=K`)**:
decode is weight-streaming-bound, so ONE fixed-k jitted verify program
replaces the decode step — each live slot's [current token + K
prompt-lookup drafts] block (`serving/spec_decode.py`, the sequence's
own history as the draft model) runs one `gpt_spec_verify` pass over
the paged cache, acceptance (exact greedy agreement) is computed
in-graph, and only consumed positions' K/V commit; rejected draft
lanes scrub to the scratch page, so a step delivers 1..K+1 tokens with
greedy output token-identical to speculation off and zero retraces as
drafts are accepted or rejected. **Chunked prefill
(`FLAGS_gen_prefill_chunk`)**: long prompts admit immediately but
prefill one fixed-size chunk per engine iteration through the
per-bucket tail programs, interleaved with decode steps — a long
prompt stops spiking every live sequence's TPOT; the slot joins decode
when its final chunk lands.

**Streaming (`submit_stream`)**: a per-token `TokenStream` fed from the
step thread — each token is staged during the iteration and delivered
only after `_record_iteration` lands (the same deferred-resolution
barrier as futures, so a consumer never observes a token the step ring
doesn't account for yet), and the final token always precedes the
future's resolution. Stream deadlines split: `ttft_timeout_ms` is HARD
(expiry before the first token cancels with `ExecutionTimeoutError`),
`timeout_ms` is SOFT once tokens flow (expiry mid-stream stops decoding
and resolves with what was delivered — tokens already left the engine
and cannot be retracted).

Hardening carries over from the one-shot engine, re-expressed at token
granularity: bounded intake (`EngineOverloaded`), worst-case page
admission control (a request is only admitted when the allocator can
cover prompt + max-new, so running sequences are never starved;
exhaustion defers admission and dumps a flight record), per-request
deadlines enforced before EVERY decode step (a mid-decode expiry
cancels just that sequence and frees its pages), poison isolation via
per-slot non-finite-logit flags (a poisoned sequence fails only its own
future; its pages are zeroed before reuse so NaNs cannot leak through
masked attention into the next owner), shutdown-drain, and
`/readyz`-compatible `health()`. TTFT/TPOT spans feed the `ttft_ms` /
`tpot_ms` histograms and `reqspan:` trace instants
(`tools/latency_report.py`).

Single-device by design: one engine owns one chip's pools and step
loop (the PR 3 lane made token-level — collector and lane collapse into
one step thread because the decode batch IS the lane). Data-parallel
scale-out = one engine per chip behind the router tier's `/readyz`.
"""
from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from ..framework import monitor
from ..framework.errors import (ExecutionTimeoutError, FatalError,
                                InvalidArgumentError,
                                ResourceExhaustedError, UnavailableError)
from ..framework.flags import flag
from ..profiler import (RecordEvent, audit, device_telemetry, exporter,
                        flight_recorder, slo, spans, step_log,
                        timeseries, trace_context)
from . import failpoints
from .kv_cache import TRASH_PAGE, PagedKVCache
from .kv_tier import HostTier
from .prefix_cache import PrefixCache
from .spec_decode import NGramProposer

# the intake queue legitimately moves both ways; registering it as an
# "updown" gauge makes the exporter render a Prometheus gauge while the
# cross-process relay keeps summing its stat_add/stat_sub deltas
# (monitor is the single registry of gauge names — ISSUE 11)
monitor.register_gauge("STAT_gen_queue_depth", updown=True)

__all__ = ["CrashManifest", "GenerationConfig", "GenerationEngine",
           "ReplayEntry", "TokenStream"]


def _now_ms() -> float:
    return time.perf_counter() * 1000.0


class GenerationConfig:
    """Continuous-batching knobs; defaults ride the FLAGS_gen_* /
    FLAGS_paged_* registry so deployments tune engines without code
    changes."""

    def __init__(self, max_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 pages_per_seq: Optional[int] = None,
                 prefill_buckets=None,
                 max_new_tokens: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 request_timeout_ms: Optional[float] = None,
                 kv_cache_dtype: Optional[str] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_max_pages: Optional[int] = None,
                 spec_k: Optional[int] = None,
                 spec_ngram: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 kv_tier: Optional[bool] = None,
                 kv_tier_host_bytes: Optional[int] = None,
                 kv_tier_chunk_pages: Optional[int] = None,
                 program_store: Optional[str] = None,
                 program_store_force: Optional[bool] = None,
                 tp: Optional[int] = None,
                 top_k: int = 0, seed: int = 0, warmup: bool = True):
        self.max_slots = int(flag("FLAGS_gen_max_slots")
                             if max_slots is None else max_slots)
        if self.max_slots < 1:
            raise InvalidArgumentError("max_slots must be >= 1")
        self.page_size = int(flag("FLAGS_paged_page_size")
                             if page_size is None else page_size)
        self.num_pages = int(flag("FLAGS_paged_num_pages")
                             if num_pages is None else num_pages)
        self.pages_per_seq = int(flag("FLAGS_paged_pages_per_seq")
                                 if pages_per_seq is None else pages_per_seq)
        if prefill_buckets is None:
            raw = str(flag("FLAGS_gen_prefill_buckets"))
            prefill_buckets = [int(x) for x in raw.split(",") if x.strip()]
        buckets = sorted({int(b) for b in prefill_buckets if int(b) >= 1})
        if not buckets:
            raise InvalidArgumentError("prefill_buckets must be non-empty")
        self.prefill_buckets = tuple(buckets)
        self.max_new_tokens = int(flag("FLAGS_gen_max_new_tokens")
                                  if max_new_tokens is None
                                  else max_new_tokens)
        self.max_queue_depth = int(flag("FLAGS_gen_max_queue_depth")
                                   if max_queue_depth is None
                                   else max_queue_depth)
        self.request_timeout_ms = float(
            flag("FLAGS_gen_request_timeout_ms")
            if request_timeout_ms is None else request_timeout_ms)
        self.kv_cache_dtype = str(flag("FLAGS_kv_cache_dtype")
                                  if kv_cache_dtype is None
                                  else kv_cache_dtype)
        if self.kv_cache_dtype not in ("auto", "int8", "float32",
                                       "bfloat16"):
            raise InvalidArgumentError(
                f"kv_cache_dtype must be auto/int8/float32/bfloat16, "
                f"got {self.kv_cache_dtype!r}")
        self.prefix_cache = bool(flag("FLAGS_gen_prefix_cache")
                                 if prefix_cache is None else prefix_cache)
        self.prefix_cache_max_pages = int(
            flag("FLAGS_gen_prefix_cache_max_pages")
            if prefix_cache_max_pages is None else prefix_cache_max_pages)
        if self.prefix_cache_max_pages < 0:
            raise InvalidArgumentError(
                "prefix_cache_max_pages must be >= 0 (0 = unbounded)")
        self.spec_k = int(flag("FLAGS_gen_spec_k")
                          if spec_k is None else spec_k)
        if self.spec_k < 0:
            raise InvalidArgumentError("spec_k must be >= 0 (0 = off)")
        self.spec_ngram = int(flag("FLAGS_gen_spec_ngram")
                              if spec_ngram is None else spec_ngram)
        if self.spec_k and self.spec_ngram < 1:
            raise InvalidArgumentError(
                "spec_ngram must be >= 1 when spec_k > 0")
        self.prefill_chunk = int(flag("FLAGS_gen_prefill_chunk")
                                 if prefill_chunk is None
                                 else prefill_chunk)
        if self.prefill_chunk < 0:
            raise InvalidArgumentError(
                "prefill_chunk must be >= 0 (0 = whole-prompt prefill)")
        # tiered KV cache (ISSUE 18): host-RAM demotion tier under the
        # prefix cache — demoted chains re-upload instead of
        # re-prefilling. The tier is a prefix-cache extension: without
        # the chain index there is nothing to demote or promote.
        self.kv_tier = bool(flag("FLAGS_kv_tier")
                            if kv_tier is None else kv_tier)
        if self.kv_tier and not self.prefix_cache:
            raise InvalidArgumentError(
                "kv_tier requires prefix_cache (the host tier demotes "
                "prefix-cache chains; enable FLAGS_gen_prefix_cache)")
        self.kv_tier_host_bytes = int(
            flag("FLAGS_kv_tier_host_bytes")
            if kv_tier_host_bytes is None else kv_tier_host_bytes)
        if self.kv_tier and self.kv_tier_host_bytes < 1:
            raise InvalidArgumentError(
                "kv_tier_host_bytes must be >= 1 when kv_tier is on")
        self.kv_tier_chunk_pages = int(
            flag("FLAGS_kv_tier_chunk_pages")
            if kv_tier_chunk_pages is None else kv_tier_chunk_pages)
        if self.kv_tier and self.kv_tier_chunk_pages < 1:
            raise InvalidArgumentError(
                "kv_tier_chunk_pages must be >= 1 when kv_tier is on")
        # warm start (ISSUE 16): root of the on-disk AOT executable
        # store; None/"" = off (device.program_store_dir resolves the
        # flag default). force engages the store even where
        # device.serialization_unsafe_backend() refuses it (XLA:CPU —
        # the PR 1 aliasing-drop corruption class, warned once)
        if program_store is None:
            from .. import device as _device
            self.program_store = _device.program_store_dir()
        else:
            self.program_store = str(program_store) or None
        self.program_store_force = bool(
            flag("FLAGS_gen_program_store_force")
            if program_store_force is None else program_store_force)
        # mesh-slice lane (ISSUE 19): tensor-parallel degree — the
        # engine builds its whole program pack sharded over a 'tp'
        # mesh axis when > 1 (or when an explicit mesh is handed to
        # GenerationEngine, which then wins over the flag/knob)
        self.tp = int(flag("FLAGS_gen_tp") if tp is None else tp)
        if self.tp < 1:
            raise InvalidArgumentError("tp must be >= 1")
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.warmup = bool(warmup)


class TokenStream:
    """Per-token delivery handle returned by
    `GenerationEngine.submit_stream`.

    Iterate it to receive generated token ids as the step thread
    decodes them (each delivered AFTER its iteration's step-ring record
    lands — the same deferred-resolution barrier futures honor);
    iteration ends after the final token, and the streamed tokens
    concatenate exactly to `result()`'s generated part. A failed
    request raises the same exception from the iterator and from
    `result()`. `result(timeout)` returns the full sequence (prompt +
    generated, numpy int32) — the final token is always queued before
    the future resolves, so a consumer woken by `result()` can drain
    the remaining tokens without blocking."""

    _END = object()

    def __init__(self, future: Future):
        self._q = _queue.SimpleQueue()
        self._exc: Optional[BaseException] = None
        self._ended = False
        self.future = future
        # fleet trace id (ISSUE 20) — set at admission so a streaming
        # caller can correlate its tokens with the merged fleet trace
        self.trace_id: Optional[str] = None

    def _put(self, item) -> None:     # engine-side (step thread)
        self._q.put(item)

    def __iter__(self):
        return self

    def __next__(self) -> int:
        if self._exc is not None:
            raise self._exc
        if self._ended:
            raise StopIteration
        item = self._q.get()
        if item is TokenStream._END:
            self._ended = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._exc = item
            raise item
        return int(item)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """The full sequence, exactly what `submit().result()` would
        have returned for the same request."""
        return self.future.result(timeout)


class _GenRequest:
    __slots__ = ("rid", "prompt", "max_new", "eos", "do_sample",
                 "temperature", "future", "deadline_ms", "t_enqueue_ms",
                 "span", "slot", "pt_row", "toks", "next_pos", "ordinal",
                 "defer_logged", "stream", "ttft_deadline_ms",
                 "prefix_tokens", "prefill_pos", "pending_digests",
                 "spec_accepted", "claimed", "retries", "skip_stream",
                 "trace_id")

    _ids = itertools.count(1)

    def __init__(self, prompt, max_new, eos, do_sample, temperature,
                 future, deadline_ms, t_enqueue_ms, span,
                 stream=None, ttft_deadline_ms=None, trace_id=None):
        self.rid = next(self._ids)
        self.prompt = prompt            # np.int32 [S]
        self.max_new = max_new
        self.eos = eos
        self.do_sample = do_sample
        self.temperature = temperature
        self.future = future
        self.deadline_ms = deadline_ms
        self.t_enqueue_ms = t_enqueue_ms
        self.span = span                # GenSpan or None
        self.slot: Optional[int] = None
        self.pt_row = None              # np.int32 [pages_per_seq]
        self.toks: List[int] = []       # generated tokens (eos included)
        self.next_pos = 0               # cache position the NEXT step writes
        self.ordinal = 0                # engine-local submit ordinal
        self.defer_logged = set()       # audit DEFER_* causes noted once
        self.stream = stream            # TokenStream or None
        self.ttft_deadline_ms = ttft_deadline_ms  # HARD (streams)
        self.prefix_tokens = 0          # prompt tokens served from cache
        self.prefill_pos = None         # chunked prefill: next prompt
        #                                 position to prefill (None =
        #                                 prefill complete / not chunked)
        self.pending_digests = None     # prompt digests held across chunks
        self.spec_accepted = 0          # draft tokens accepted (ISSUE 14)
        self.claimed = False            # future claimed running (_admit)
        self.retries = 0                # supervised restarts survived
        self.skip_stream = 0            # stream tokens to suppress on a
        #                                 from-scratch greedy replay
        #                                 (exactly-once across restarts)
        self.trace_id = trace_id        # fleet trace id (ISSUE 20) —
        #                                 survives replay so one id
        #                                 spans every incarnation


class ReplayEntry:
    """One request's restartable state inside a `CrashManifest`
    (ISSUE 15): the immutable submit parameters verbatim, the generated
    prefix so a live slot replays as a prompt+generated continuation,
    the preserved future/stream the caller still holds, and the
    bookkeeping exactly-once replay needs (`delivered` streamed tokens,
    `claimed` future state, the `retries` budget already spent)."""

    __slots__ = ("rid", "ordinal", "prompt", "toks", "max_new", "eos",
                 "do_sample", "temperature", "future", "stream",
                 "deadline_ms", "ttft_deadline_ms", "t_enqueue_ms",
                 "claimed", "retries", "delivered", "queued", "trace_id")

    def __init__(self, req: "_GenRequest", queued: bool):
        self.rid = req.rid
        self.ordinal = req.ordinal
        self.prompt = req.prompt
        self.toks = list(req.toks)
        self.max_new = req.max_new
        self.eos = req.eos
        self.do_sample = req.do_sample
        self.temperature = req.temperature
        self.future = req.future
        self.stream = req.stream
        self.deadline_ms = req.deadline_ms
        self.ttft_deadline_ms = req.ttft_deadline_ms
        self.t_enqueue_ms = req.t_enqueue_ms
        self.claimed = req.claimed
        self.retries = req.retries
        # _die flushes the staged stream queue before the manifest is
        # built, so every generated token was either delivered or —
        # during a from-scratch replay — SUPPRESSED because an earlier
        # incarnation already delivered it (skip_stream counts the
        # suppressions still owed). Total tokens the CALLER has seen =
        # generated here + still-owed suppressions; dropping the
        # residual would re-deliver tokens if THIS replay dies too.
        self.delivered = (len(req.toks) + req.skip_stream
                          if req.stream is not None else 0)
        self.queued = queued
        self.trace_id = req.trace_id    # one trace id per request,
        #                                 across every incarnation


class CrashManifest:
    """Everything `EngineSupervisor` needs to resurrect a dead engine
    (ISSUE 15): the replayable requests in original admission order
    (live slots first, then the still-queued tail), the fatal error,
    the KV-pool postmortem snapshot, the compile ledger at death (the
    zero-new-traces baseline the rebuilt engine is held to), and the
    degraded-mode state that must survive the restart."""

    __slots__ = ("engine", "incarnation", "error", "entries",
                 "degraded_spec_off", "kv", "compiles")

    def __init__(self, engine: str, incarnation: int,
                 error: BaseException, entries: List[ReplayEntry],
                 degraded_spec_off: bool, kv: dict, compiles: dict):
        self.engine = engine
        self.incarnation = incarnation
        self.error = error
        self.entries = entries
        self.degraded_spec_off = degraded_spec_off
        self.kv = kv
        self.compiles = compiles

    def summary(self) -> dict:
        """Flight-dump payload: counts + per-entry state, no futures."""
        return {
            "engine": self.engine, "incarnation": self.incarnation,
            "error": repr(self.error),
            "entries": [{"rid": e.rid, "queued": e.queued,
                         "generated": len(e.toks),
                         "delivered": e.delivered,
                         "stream": e.stream is not None,
                         "retries": e.retries}
                        for e in self.entries],
            "degraded_spec_off": self.degraded_spec_off,
            "kv": self.kv, "compiles": dict(self.compiles)}


class _ProgramPack:
    """The engine's jitted program set + its exactly-once compile
    ledger, shareable across supervised-restart incarnations
    (ISSUE 15). `jax.jit` caches compiled executables on the WRAPPER
    object, so a rebuilt engine that reuses the same wrappers (same
    config, same model → identical signatures) re-warms entirely from
    cache: zero new in-process traces, and because the ledger dict is
    owned here — not by any one engine — the shared count proves it.

    ISSUE 16 adds the cross-PROCESS half: `execs` maps program name
    (the ledger's own keys) → the AOT `jax.stages.Compiled` the engine
    resolved at warmup — store-loaded OR live-compiled-and-written-back
    — and `loaded` counts the store loads the way `ledger` counts
    traces. A resurrection adopts both, so a supervised rebuild of a
    store-started engine still performs zero traces AND zero disk
    loads."""

    __slots__ = ("ledger", "loaded", "execs", "prefill", "tail",
                 "decode", "verify", "zero", "cow", "npool", "W",
                 "tier_gather", "tier_write")

    def __init__(self, ledger, prefill, tail, decode, verify, zero, cow,
                 npool, W, loaded=None, execs=None, tier_gather=None,
                 tier_write=None):
        self.ledger = ledger
        self.loaded = {} if loaded is None else loaded
        self.execs = {} if execs is None else execs
        self.prefill = prefill
        self.tail = tail
        self.decode = decode
        self.verify = verify
        self.zero = zero
        self.cow = cow
        self.npool = npool
        self.W = W
        # tiered KV cache (ISSUE 18): ride the pack like every other
        # wrapper, or a supervised restart would retrace them
        self.tier_gather = tier_gather
        self.tier_write = tier_write


class GenerationEngine:
    """Token-level continuous-batching front-end over a
    `models.GPTForCausalLM`.

    `submit(prompt_ids, ...)` returns a `concurrent.futures.Future`
    resolving to the full token sequence (prompt + generated, numpy
    int32). Greedy by default; `do_sample=True` draws from the
    temperature-scaled distribution using the ENGINE's PRNG stream
    (`config.seed` folded with the step counter — per-request seeds
    don't exist because co-resident sequences share each step's
    program).

    Scheduling contract: admission is FIFO with head-of-line blocking —
    a request is admitted the moment a slot AND its worst-case pages
    (prompt + max_new) are both available, prefills immediately, and
    joins the very next decode step. Deadlines are whole-request and
    checked before every step; an expired sequence is cancelled
    mid-decode with nothing delivered (deadline semantics are
    streaming-unsafe by design — there is no partial result).

    Numerics: decode always runs the one compiled [max_slots] program,
    so a sequence's tokens are independent of WHO shares the batch
    (row-independent math) and bit-stable across repeats on one engine
    config. Comparisons against `GPTModel.generate` cross program/shape
    boundaries and hold at token level (greedy) / float tolerance, per
    the standard XLA per-shape caveat.
    """

    def __init__(self, model, config: Optional[GenerationConfig] = None,
                 name: str = "generation", device=None, mesh=None,
                 metrics_port: Optional[int] = None,
                 incarnation: int = 0, on_death=None, _carryover=None,
                 **overrides):
        if config is None:
            config = GenerationConfig(**overrides)
        elif overrides:
            raise InvalidArgumentError(
                "pass either a GenerationConfig or keyword overrides, "
                "not both")
        import copy
        self._cfg = copy.copy(config)
        self.name = name
        # supervised-restart seam (ISSUE 15, serving/supervisor.py):
        # `incarnation` is this engine generation's ordinal (rides every
        # step-ring record + reqspan so reports distinguish
        # generations); `on_death` — when set — makes _die hand a
        # CrashManifest to the supervisor instead of stranding work,
        # and the supervisor (not this engine) owns the exporter
        # registration; `_carryover` passes the previous incarnation's
        # program pack + step/audit rings + degraded state forward
        self.incarnation = int(incarnation)
        self._on_death = on_death
        carry = _carryover or {}
        from ..models.gpt import GPTForCausalLM
        if not isinstance(model, GPTForCausalLM):
            raise InvalidArgumentError(
                f"GenerationEngine serves a models.GPTForCausalLM "
                f"(got {type(model).__name__})")
        self._model = model
        mcfg = model.gpt.config
        pack: Optional[_ProgramPack] = carry.get("pack")
        # raises for MoE; a resurrection reuses the pack's exact weight
        # pytree so the rebuilt programs see identical leaves
        self._W = pack.W if pack is not None else model.decode_weights()
        self._H = mcfg.num_heads
        self._D = mcfg.hidden_size // mcfg.num_heads
        self._scale = 1.0 / self._D ** 0.5
        self._max_position = mcfg.max_position_embeddings
        # mesh-slice lane (ISSUE 19): tp > 1 generalizes the lane from
        # one chip to a mesh slice — every program rebuilds as a
        # shard_map program over the 'tp' axis with projections and KV
        # pools head-sharded, partial sums psum-reduced once per block.
        # An explicit `mesh` wins over FLAGS_gen_tp/config.tp and must
        # carry a 'tp' axis; without one the engine builds its own
        # slice from the first `tp` visible devices.
        if mesh is not None:
            if "tp" not in mesh.shape:
                raise InvalidArgumentError(
                    f"GenerationEngine mesh needs a 'tp' axis (got "
                    f"{tuple(mesh.axis_names)})")
            self._mesh = mesh
            self._tp = int(mesh.shape["tp"])
        else:
            self._tp = int(self._cfg.tp)
            if self._tp > 1:
                from ..parallel.spmd import tp_mesh
                self._mesh = tp_mesh(self._tp)
            else:
                self._mesh = None
        self._cfg.tp = self._tp
        if self._H % self._tp != 0:
            raise InvalidArgumentError(
                f"num_heads={self._H} not divisible by tp={self._tp} — "
                f"head-sharded lanes need equal slices")
        if self._tp > 1 and pack is None:
            # one-time placement: head-sharded projection leaves,
            # replicated embeddings/LNs (a resurrection's pack.W is
            # already placed — reuse keeps leaves identical)
            from ..models.gpt import shard_decode_weights
            self._W = shard_decode_weights(self._W, self._mesh)
        if self._cfg.pages_per_seq <= 0:
            self._cfg.pages_per_seq = -(-self._max_position
                                        // self._cfg.page_size)
        # buckets are bounded by the PER-SEQUENCE page capacity too, not
        # just max_position: a wider bucket would compute page indices
        # past the table width, which the gather CLAMPS onto the
        # sequence's last real page — pad-token K/V would silently
        # overwrite prompt state there
        cap = min(self._max_position,
                  self._cfg.pages_per_seq * self._cfg.page_size)
        self._cfg.prefill_buckets = tuple(sorted(
            {min(int(b), cap) for b in self._cfg.prefill_buckets}))
        self._device = device
        dtype = np.asarray(self._W["lnf"][0]).dtype
        kv_dtype = (str(dtype) if self._cfg.kv_cache_dtype == "auto"
                    else self._cfg.kv_cache_dtype)
        self._cache = PagedKVCache(
            mcfg.num_layers, self._H, self._D, self._cfg.page_size,
            self._cfg.num_pages, self._cfg.pages_per_seq, dtype=kv_dtype,
            mesh=self._mesh)
        # int8 page mode: quantize-on-append decode/prefill programs
        # thread the parallel scale pools (donated alongside the pages);
        # everything above this line — admission arithmetic, page
        # tables, zero-on-free, the compile ledger — is dtype-blind
        self._quant_kv = self._cache.quantized
        self._kp = self._cache.k_pages
        self._vp = self._cache.v_pages
        self._ks = self._cache.k_scales
        self._vs = self._cache.v_scales
        # prefix cache (ISSUE 12): content-hash chain index over the
        # refcounted pages; None keeps the PR 8 ownership semantics
        # exactly (every page refcount 1, nothing cached or shared)
        self._prefix = (PrefixCache(
            self._cache, name,
            max_pages=self._cfg.prefix_cache_max_pages)
            if self._cfg.prefix_cache else None)
        # tiered KV cache (ISSUE 18): bounded host-RAM store the prefix
        # cache demotes cold chains into instead of discarding them —
        # attach_tier (below, once the audit ring exists) wires the
        # demote-gather and audit hooks
        self._tier = (HostTier(self._cfg.kv_tier_host_bytes, name)
                      if (self._cfg.kv_tier and self._prefix is not None)
                      else None)
        # chunked prefill (ISSUE 14): chunks ride the per-bucket tail
        # programs, so a chunk can never be wider than the largest
        # bucket; 0 keeps whole-prompt prefill at admission
        self._cfg.prefill_chunk = min(self._cfg.prefill_chunk,
                                      self._cfg.prefill_buckets[-1])
        # the tail-extension programs serve BOTH prefix-cache hits and
        # prefill chunks — warmed whenever either consumer exists
        self._use_tail = (self._prefix is not None
                          or self._cfg.prefill_chunk > 0)
        # speculative decoding (ISSUE 14): model-free prompt-lookup
        # drafts + ONE fixed-k verify program replacing the decode step
        self._spec_k = self._cfg.spec_k
        self._proposer = (NGramProposer(self._cfg.spec_ngram)
                          if self._spec_k else None)
        self._spec_drafted_total = 0
        self._spec_accepted_total = 0
        self._chunks_total = 0

        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._slots: List[Optional[_GenRequest]] = \
            [None] * self._cfg.max_slots
        self._closed = False
        self._abort = False
        # futures whose resolution is held until this iteration's
        # step-ring record lands (step-thread only; see _resolve_later)
        self._resolve_q: List[tuple] = []
        # streamed tokens / end markers staged the same way — flushed
        # BEFORE the futures, so a stream's final token always precedes
        # its future's resolution (step-thread only)
        self._stream_q: List[tuple] = []
        self._warmed = False
        self._steps_total = 0
        self._prefills_total = 0
        self._tokens_total = 0
        self._exhaust_dumped = False   # one flight dump per episode
        self._req_seq = 0              # engine-local submit ordinal
        self._ledger = {}              # "decode[m=M]"/"prefill[b=S]" -> traces
        self._death: Optional[BaseException] = None
        self._pre_step_hook = None     # test seam: runs on the step thread
        self._hist = monitor.histogram(f"{name}_request_ms")
        self._base_key = None          # PRNGKey, built lazily on first use
        # degraded modes (ISSUE 15): detector knobs snapshotted at
        # construction (a runtime flag flip must not flip speculation
        # onto an un-warmed program); the spec-off verdict itself rides
        # the crash manifest so a restart stays degraded
        self._poison_degrade_k = int(flag("FLAGS_gen_poison_degrade_k"))
        self._exhaust_clamp_k = int(flag("FLAGS_gen_exhaust_clamp_k"))
        self._degraded_window_s = float(flag("FLAGS_gen_degraded_window_s"))
        self._degraded_spec_off = bool(carry.get("degraded_spec_off"))
        self._poison_times: deque = deque()
        self._exhaust_times: deque = deque()
        self._admit_clamped = False
        # scheduler X-ray (ISSUE 11): decision audit ring (always on —
        # one deque append per decision) + per-iteration step ring
        # (FLAGS_gen_step_log; snapshot at construction so one engine's
        # A/B arm can't half-enable the other's). A resurrection reuses
        # the previous incarnation's rings: the restart's own events
        # land in the SAME postmortem trail as the death that caused it
        self._audit = carry.get("audit") or audit.AuditLog(name)
        if self._tier is not None:
            # demote-on-evict (ISSUE 18): evictions now gather page
            # content off-device into the host store before freeing HBM
            self._prefix.attach_tier(self._tier, self._tier_gather_page,
                                     audit=self._audit)
        self._step_log = carry.get("step_log") or (
            step_log.StepLog(name) if step_log.enabled() else None)
        if carry.get("step_log") is not None:
            # re-register the carried ring: a failed rebuild attempt's
            # error path unregisters it, and the retry must restore it
            step_log.register(self._step_log)
        self._iters = 0
        # last-seen cumulative tier counters — _record_iteration takes
        # deltas so the step ring carries per-iteration demote/promote
        # counts without a second bookkeeping path
        self._tier_counts = (0, 0)
        self._it = {"admitted": 0, "completed": 0, "expired": 0,
                    "poisoned": 0, "aborted": 0, "freed": 0,
                    "prefix_tokens": 0, "cow_splits": 0,
                    "tokens": 0, "spec_drafted": 0, "spec_accepted": 0,
                    "prefill_chunks": 0,
                    "prefill_ms": 0.0, "decode_ms": 0.0,
                    "promote_ms": 0.0,
                    "attr_idle_ms": 0.0, "attr_sched_ms": 0.0,
                    "attr_wall_ms": 0.0}
        # published BEFORE the step thread exists so a router polling a
        # freshly built replica reads a truthful empty-engine snapshot
        self._pressure = self._compute_pressure()

        self._build_programs(pack)
        flight_recorder.touch()
        device_telemetry.touch()
        timeseries.touch()
        if self._on_death is None:
            # supervised engines never register themselves: the
            # SUPERVISOR is the stable /readyz + /stats entity across
            # incarnations (a restarted engine re-registering would
            # evict it from the exporter's name-keyed registry)
            exporter.register_engine(self)
        try:
            if self._cfg.warmup:
                self._warmup()
            self._warmed = True
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=f"{name}-genstep")
            self._thread.start()
            self._owns_metrics_server = (metrics_port is not None
                                         and int(metrics_port) == 0)
            self.metrics_server = None
            self.metrics_server = exporter.start_metrics_server(
                metrics_port)
        except Exception:
            exporter.unregister_engine(self)  # identity-guarded no-op
            #                                   for supervised engines
            if self._step_log is not None:
                step_log.unregister(self._step_log)
            raise

    # -- jitted programs ---------------------------------------------------

    def _pools(self):
        """The donated device-pool tuple the jitted programs thread:
        (k_pages, v_pages) — plus the parallel scale pools in the int8
        page mode."""
        if self._quant_kv:
            return (self._kp, self._vp, self._ks, self._vs)
        return (self._kp, self._vp)

    def _set_pools(self, pools):
        if self._quant_kv:
            self._kp, self._vp, self._ks, self._vs = pools
        else:
            self._kp, self._vp = pools

    def _build_programs(self, pack: Optional[_ProgramPack] = None):
        if pack is not None:
            # resurrection path (ISSUE 15): adopt the previous
            # incarnation's jit wrappers and SHARE its ledger dict —
            # warmup re-runs against the jit caches (identical
            # signatures), so the ledger not moving IS the
            # zero-new-traces proof
            self._ledger = pack.ledger
            self._npool = pack.npool
            self._prefill_jit = pack.prefill
            self._tail_jit = pack.tail
            self._decode_jit = pack.decode
            self._verify_jit = pack.verify
            self._zero_jit = pack.zero
            self._cow_jit = pack.cow
            self._tier_gather_jit = pack.tier_gather
            self._tier_write_jit = pack.tier_write
            # ISSUE 16: adopt the resolved AOT executables + the load
            # ledger too — a resurrection of a store-started engine
            # re-warms through `execs` directly: zero traces AND zero
            # disk loads (rebuilds prefer the pack, the pack prefers
            # the store)
            self._execs = pack.execs
            self._loaded = pack.loaded
            self._store = None
            self._pack = pack
            return
        import jax
        import jax.numpy as jnp

        from ..models.gpt import (gpt_decode_step, gpt_logits,
                                  gpt_prefill, gpt_prefill_extend,
                                  gpt_spec_verify)
        from ..ops.paged_ops import (page_rows_for_positions,
                                     paged_attention, paged_gather,
                                     paged_gather_layers,
                                     paged_gather_quantized,
                                     paged_prefix_attention, paged_write,
                                     paged_write_quantized)

        tp, mesh = self._tp, self._mesh
        # mesh-slice lane (ISSUE 19): under shard_map every closure sees
        # PER-SHARD tensors, so H is the LOCAL head count (head_dim —
        # and with it `scale` — is untouched by head sharding) and
        # `psum` is the once-per-block partial-sum reduction the
        # row-parallel projections apply before their replicated bias
        H = self._H // tp
        P, scale = self._cfg.page_size, self._scale
        psum = (lambda x: jax.lax.psum(x, "tp")) if tp > 1 else None
        top_k = self._cfg.top_k
        quant = self._quant_kv
        # pools per program signature: (kp, vp) or (kp, vp, ks, vs) —
        # the int8 mode's scale pools ride (and are donated) alongside
        # the pages so quantize-on-append updates both in place
        NP = self._npool = 4 if quant else 2
        # the trace-time closures capture the LEDGER and scalars, never
        # the engine object: the pack outlives any one incarnation, and
        # a closure pinning the dead engine would pin its pools too
        ledger = self._ledger
        max_position = self._max_position

        def note(key: str):
            # runs at TRACE time only (python side effect under jit),
            # so the pack-owned ledger counts compiles exactly — the
            # same accounting trick as Predictor.compile_count
            ledger[key] = ledger.get(key, 0) + 1
            monitor.stat_add("STAT_gen_compiles")

        def write_pages(pools, layer, page_ids, offs, k, v,
                        requant=False):
            # requant=True only in the tail program: a CoW split page
            # arrives with content + scale, every other prefill target
            # is freshly zeroed (trace-time switch — the full-prefill
            # program carries no whole-page requant traffic)
            if quant:
                kp, vp, ksc, vsc = pools
                kp, ksc = paged_write_quantized(kp, ksc, layer, page_ids,
                                                offs, k, requant=requant)
                vp, vsc = paged_write_quantized(vp, vsc, layer, page_ids,
                                                offs, v, requant=requant)
                return (kp, vp, ksc, vsc)
            kp, vp = pools
            # a forced narrower page dtype (kv_cache_dtype="bfloat16"
            # under an fp32 model) is a deliberate storage downcast
            return (paged_write(kp, layer, page_ids, offs,
                                k.astype(kp.dtype)),
                    paged_write(vp, layer, page_ids, offs,
                                v.astype(vp.dtype)))

        def prefill_fn(W, *rest):
            pools, (pt_row, ids, length) = rest[:NP], rest[NP:]
            note(f"prefill[b={ids.shape[1]}]")
            h, ks, vs = gpt_prefill(W, ids, num_heads=H, scale=scale,
                                    reduce=psum)
            S_b = ids.shape[1]
            pos = jnp.arange(S_b)
            page_ids, offs = page_rows_for_positions(pt_row, pos, P)
            # bucket-pad tail positions (pos >= length) write to the
            # reserved scratch page, never the sequence's own pages —
            # the documented contract, and load-bearing in the int8
            # mode: the scatter-max page scales must not bake pad-token
            # K/V magnitudes into a real page's quantization grid (the
            # grid only ever widens, so the pollution would be
            # permanent; fp32 merely overwrites the junk later)
            valid = pos < length
            page_ids = jnp.where(valid, page_ids, TRASH_PAGE)
            offs = jnp.where(valid, offs, 0)
            pools = write_pages(pools, None, page_ids, offs,
                                ks[:, 0], vs[:, 0])
            idx = jnp.clip(length - 1, 0, S_b - 1)
            return (*pools, gpt_logits(W, h[0, idx]))

        def tail_prefill_fn(W, *rest):
            """Prefix-hit prefill: only the prompt TAIL runs the model —
            queries attend the cached prefix pages READ-ONLY plus their
            own in-flight K/V, and the writes land in the tail's pages
            (bucket-pad positions routed to the scratch page, exactly
            the full-prefill contract — a shared page never receives a
            pad write). One compiled program per tail bucket."""
            pools = rest[:NP]
            pt_row, ids, length, offset = rest[NP:]
            note(f"prefill_tail[b={ids.shape[1]}]")
            S_b = ids.shape[1]
            ar = jnp.arange(S_b)
            valid = ar < length
            # pad positions clamp to 0 so neither the wpe gather nor the
            # page-index arithmetic ever reads out of range; their
            # writes go to the scratch page below regardless
            positions = jnp.where(valid, offset + ar, 0)
            # gather the sequence's cached pages ONCE across all layers
            # (dequantizing in the int8 mode) — per-layer pool slices
            # would copy the whole layer buffer per layer, costing more
            # than the tail's compute
            if quant:
                kp, vp, ksc, vsc = pools
                kb_all = paged_gather_layers(kp, pt_row, ksc)
                vb_all = paged_gather_layers(vp, pt_row, vsc)
            else:
                kp, vp = pools
                kb_all = paged_gather_layers(kp, pt_row)
                vb_all = paged_gather_layers(vp, pt_row)

            def ctx_attend(layer, q, k, v):
                return paged_prefix_attention(
                    q, kb_all[layer][None], vb_all[layer][None],
                    k, v, offset, scale)

            h, ks, vs = gpt_prefill_extend(W, ids, positions, ctx_attend,
                                           num_heads=H, scale=scale,
                                           reduce=psum)
            page_ids, offs = page_rows_for_positions(pt_row, positions, P)
            page_ids = jnp.where(valid, page_ids, TRASH_PAGE)
            offs = jnp.where(valid, offs, 0)
            pools = write_pages(pools, None, page_ids, offs,
                                ks[:, 0], vs[:, 0], requant=True)
            idx = jnp.clip(length - 1, 0, S_b - 1)
            return (*pools, gpt_logits(W, h[0, idx]))

        def cow_fn(*rest):
            """Copy-on-write page split: clone one page's content across
            every layer/head from `src` to `dst` — including the
            per-(layer, head, page) scale rows in the int8 mode, so the
            private copy dequantizes identically to the shared
            original."""
            pools = rest[:NP]
            src, dst = rest[NP], rest[NP + 1]
            note("cow_copy")
            if quant:
                kp, vp, ksc, vsc = pools
                return (kp.at[:, :, dst].set(kp[:, :, src]),
                        vp.at[:, :, dst].set(vp[:, :, src]),
                        ksc.at[:, :, dst].set(ksc[:, :, src]),
                        vsc.at[:, :, dst].set(vsc[:, :, src]))
            kp, vp = pools
            return (kp.at[:, :, dst].set(kp[:, :, src]),
                    vp.at[:, :, dst].set(vp[:, :, src]))

        def write_kv(cache, layer, k, v, pos):
            pools, pt = cache
            page_ids, offs = page_rows_for_positions(pt, pos, P)
            return (write_pages(pools, layer, page_ids, offs, k, v), pt)

        def attend(cache, layer, q, pos):
            pools, pt = cache
            if quant:
                kp, vp, ksc, vsc = pools
                return paged_attention(q, kp[layer], vp[layer], pt, pos,
                                       scale, ksc[layer], vsc[layer])
            kp, vp = pools
            return paged_attention(q, kp[layer], vp[layer], pt, pos, scale)

        def decode_fn(W, *rest):
            pools = rest[:NP]
            pt, tok, pos, active, temps, smask, key = rest[NP:]
            note(f"decode[m={tok.shape[0]}]")
            logits, (pools, _) = gpt_decode_step(
                W, tok, pos, (pools, pt), write_kv, attend,
                num_heads=H, scale=scale, reduce=psum)
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            lg = logits / jnp.maximum(temps[:, None], 1e-6)
            if top_k:
                kth = jax.lax.top_k(lg, int(top_k))[0][..., -1:]
                lg = jnp.where(lg < kth, -1e30, lg)
            sampled = jax.random.categorical(key, lg).astype(jnp.int32)
            nxt = jnp.where(smask, sampled, greedy)
            bad = active & ~jnp.all(jnp.isfinite(logits), axis=-1)
            return (*pools, jnp.where(active, nxt, 0), bad)

        def verify_fn(W, *rest):
            """Speculative verify step (ISSUE 14): score every live
            slot's [current token + k drafts] block — k+1 positions —
            in ONE pass over the paged cache (`gpt_spec_verify` on the
            `_gen_block_pass` seam), accept the longest greedily-
            agreeing draft prefix IN-GRAPH, and commit only the
            consumed positions' K/V: rejected draft lanes, inactive
            slots and clamped pad positions all scrub to the reserved
            scratch page. That routing IS the rollback — a rejected
            draft never dirties a real page, so the int8 scale grids
            never widen from a token that was not kept and the PR 12
            CoW/sharing invariants hold untouched (writes always land
            past any shared prefix). Block queries attend the cached
            pages READ-ONLY (per-slot prefix length = the slot's cache
            position) plus the block's own in-flight K/V — the
            `paged_prefix_attention` oracle, so greedy output is
            token-identical to the plain decode program. Returns
            (*pools, n_accepted [M], next_token [M], bad [M])."""
            pools = rest[:NP]
            pt, toks_blk, dmask, pos0, active, temps, smask, key = \
                rest[NP:]
            note(f"verify[k={toks_blk.shape[1] - 1}]")
            M, K1 = toks_blk.shape
            # pad/overflow positions clamp into wpe range; their writes
            # are scratch-routed below regardless (the engine truncates
            # real drafts to the request's token budget, so every
            # CONSUMED position is in range by construction)
            positions = jnp.clip(pos0[:, None] + jnp.arange(K1)[None, :],
                                 0, max_position - 1)

            def ctx_attend(layer, q, k, v):
                if quant:
                    kp, vp, ksc, vsc = pools
                    kb = paged_gather_quantized(kp[layer], ksc[layer],
                                                pt, q.dtype)
                    vb = paged_gather_quantized(vp[layer], vsc[layer],
                                                pt, q.dtype)
                else:
                    kp, vp = pools
                    kb = paged_gather(kp[layer], pt)
                    vb = paged_gather(vp[layer], pt)
                return paged_prefix_attention(q, kb, vb, k, v, pos0,
                                              scale)

            h, ks, vs = gpt_spec_verify(W, toks_blk, positions,
                                        ctx_attend, num_heads=H,
                                        reduce=psum)
            logits = gpt_logits(W, h)                       # [M, K1, V]
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            # n_acc = longest prefix of drafts the model agrees with
            # (greedy[j] is the model's token AFTER position j, so
            # draft j+1 is accepted iff it equals greedy[j])
            agree = (greedy[:, :-1] == toks_blk[:, 1:]) & dmask
            n_acc = jnp.sum(jnp.cumprod(agree.astype(jnp.int32),
                                        axis=1), axis=1).astype(jnp.int32)
            # sampled slots take no drafts (greedy acceptance would
            # bias the distribution); they ride the verify program as
            # plain one-token decode with the decode program's
            # temperature/top-k sampling expression
            n_acc = jnp.where(smask, 0, n_acc)
            bonus = jnp.take_along_axis(greedy, n_acc[:, None], 1)[:, 0]
            lg0 = logits[:, 0] / jnp.maximum(temps[:, None], 1e-6)
            if top_k:
                kth = jax.lax.top_k(lg0, int(top_k))[0][..., -1:]
                lg0 = jnp.where(lg0 < kth, -1e30, lg0)
            sampled = jax.random.categorical(key, lg0).astype(jnp.int32)
            nxt = jnp.where(smask, sampled, bonus)
            nxt = jnp.where(active, nxt, 0)
            consumed = jnp.arange(K1)[None, :] <= n_acc[:, None]
            finite = jnp.all(jnp.isfinite(logits), axis=-1)  # [M, K1]
            bad = active & jnp.any(consumed & ~finite, axis=1)
            commit = consumed & active[:, None]
            page_ids, offs = page_rows_for_positions(pt, positions, P)
            page_ids = jnp.where(commit, page_ids, TRASH_PAGE)
            offs = jnp.where(commit, offs, 0)
            L, D = ks.shape[0], ks.shape[-1]
            # [L, M, H, K1, D] -> [L, H, M*K1, D]: the prefill-shaped
            # all-layers scatter
            ksf = jnp.moveaxis(ks, 1, 2).reshape(L, H, M * K1, D)
            vsf = jnp.moveaxis(vs, 1, 2).reshape(L, H, M * K1, D)
            # requant=True: commits land on the slot's current partial
            # page, which already holds content (and, int8, a non-zero
            # scale) — the tail-prefill contract, not the fresh-page one
            pools = write_pages(pools, None, page_ids.reshape(-1),
                                offs.reshape(-1), ksf, vsf, requant=True)
            return (*pools, n_acc, nxt, bad)

        def zero_fn(*rest):
            # trash-padded page rows: the scratch page is re-zeroed with
            # every free, which also scrubs poisoned prefill tails; the
            # int8 mode resets the freed pages' SCALES too, so the next
            # owner starts from a clean quantization grid and a poisoned
            # page's scale can't survive its content
            pools, pages = rest[:NP], rest[NP]
            if quant:
                kp, vp, ksc, vsc = pools
                return (kp.at[:, :, pages].set(0),
                        vp.at[:, :, pages].set(0),
                        ksc.at[:, :, pages].set(0.0),
                        vsc.at[:, :, pages].set(0.0))
            kp, vp = pools
            return (kp.at[:, :, pages].set(0.0),
                    vp.at[:, :, pages].set(0.0))

        def tier_gather_fn(*rest):
            """Demotion gather (ISSUE 18): copy ONE page's raw blocks —
            and, in the int8 mode, its per-(layer, head) scale rows —
            out of the pools for the host tier. NON-donating by
            contract: the pools are kept (the content is being copied
            off-device, the page frees through the ordinary eviction
            path right after), which is also why this program can never
            ride the program store — `_selfcheck_alias` requires every
            covered program to donate its pools."""
            pools, page = rest[:NP], rest[NP]
            note("tier_gather")
            if quant:
                kp, vp, ksc, vsc = pools
                return (kp[:, :, page], vp[:, :, page],
                        ksc[:, :, page], vsc[:, :, page])
            kp, vp = pools
            return (kp[:, :, page], vp[:, :, page])

        def tier_write_fn(*rest):
            """Promotion scatter (ISSUE 18): write one fixed-width
            chunk of host-tier pages — raw content, raw int8 scale rows
            — into the admission's fresh target pages. Pad rows route
            to the reserved scratch page with zero content, the
            standard pad contract, so the ONE compiled width
            (kv_tier_chunk_pages) covers every promotion length with
            zero retraces."""
            pools = rest[:NP]
            note(f"tier_write[w={rest[NP].shape[0]}]")
            if quant:
                pages, kb, vb, ksb, vsb = rest[NP:]
                kp, vp, ksc, vsc = pools
                return (kp.at[:, :, pages].set(jnp.moveaxis(kb, 0, 2)),
                        vp.at[:, :, pages].set(jnp.moveaxis(vb, 0, 2)),
                        ksc.at[:, :, pages].set(jnp.moveaxis(ksb, 0, 2)),
                        vsc.at[:, :, pages].set(jnp.moveaxis(vsb, 0, 2)))
            pages, kb, vb = rest[NP:]
            kp, vp = pools
            return (kp.at[:, :, pages].set(jnp.moveaxis(kb, 0, 2)),
                    vp.at[:, :, pages].set(jnp.moveaxis(vb, 0, 2)))

        if tp > 1:
            # partition every program over the 'tp' mesh axis: W enters
            # under the Megatron specs, the pools (and int8 scale
            # grids) head-sharded, page tables / token ids / scalars /
            # PRNG keys replicated, and the logits (psum-reduced inside
            # the blocks) leave replicated — each donated sharded pool
            # aliases straight into its identically-sharded output
            from jax.sharding import PartitionSpec as PS

            from ..models.gpt import decode_weight_specs
            from ..parallel.spmd import compat_shard_map
            rep = PS()
            wspec = decode_weight_specs(self._W)
            pool5 = PS(None, "tp", None, None, None)   # [L,H,N,Pg,D]
            grid3 = PS(None, "tp", None)               # [L,H,N]
            pspecs = ((pool5, pool5, grid3, grid3) if quant
                      else (pool5, pool5))
            page4 = PS(None, "tp", None, None)         # one page [L,H,Pg,D]
            page2 = PS(None, "tp")                     # scale row [L,H]
            chunk5 = PS(None, None, "tp", None, None)  # [W,L,H,Pg,D]
            chunk3 = PS(None, None, "tp")              # [W,L,H]

            def shard(fn, extras, outs, with_w=True):
                ins = ((wspec,) if with_w else ()) + pspecs + extras
                return compat_shard_map(fn, mesh=mesh, in_specs=ins,
                                        out_specs=outs, check=False)

            prefill_fn = shard(prefill_fn, (rep,) * 3, (*pspecs, rep))
            tail_prefill_fn = shard(tail_prefill_fn, (rep,) * 4,
                                    (*pspecs, rep))
            decode_fn = shard(decode_fn, (rep,) * 7,
                              (*pspecs, rep, rep))
            verify_fn = shard(verify_fn, (rep,) * 8,
                              (*pspecs, rep, rep, rep))
            cow_fn = shard(cow_fn, (rep,) * 2, pspecs, with_w=False)
            zero_fn = shard(zero_fn, (rep,), pspecs, with_w=False)
            # tier seam (ISSUE 18): the host store keeps FULL pages —
            # the gather's sharded out_specs reassemble every head
            # shard into one host block, and the write's chunk specs
            # split the staged full blocks back across the slice
            tier_gather_fn = shard(
                tier_gather_fn, (rep,),
                (page4, page4, page2, page2) if quant
                else (page4, page4), with_w=False)
            tier_write_fn = shard(
                tier_write_fn,
                (rep, chunk5, chunk5, chunk3, chunk3) if quant
                else (rep, chunk5, chunk5),
                pspecs, with_w=False)

        donate = tuple(range(1, 1 + NP))
        self._prefill_jit = jax.jit(prefill_fn, donate_argnums=donate)
        self._tail_jit = jax.jit(tail_prefill_fn, donate_argnums=donate)
        self._decode_jit = jax.jit(decode_fn, donate_argnums=donate)
        self._verify_jit = (jax.jit(verify_fn, donate_argnums=donate)
                            if self._spec_k else None)
        self._zero_jit = jax.jit(zero_fn,
                                 donate_argnums=tuple(range(NP)))
        self._cow_jit = jax.jit(cow_fn, donate_argnums=tuple(range(NP)))
        self._tier_gather_jit = (jax.jit(tier_gather_fn)
                                 if self._tier is not None else None)
        self._tier_write_jit = (
            jax.jit(tier_write_fn, donate_argnums=tuple(range(NP)))
            if self._tier is not None else None)
        # warm start (ISSUE 16): resolved AOT executables by program
        # name (ledger keys) + the store-load ledger; warmup fills them
        self._execs = {}
        self._loaded = {}
        self._store = None
        if self._cfg.program_store:
            from .program_store import ProgramStore
            self._store = ProgramStore(
                self._cfg.program_store, self._store_key_material(),
                force=self._cfg.program_store_force)
            if self._store.refused:
                self._store = None
        self._pack = _ProgramPack(
            ledger=self._ledger, prefill=self._prefill_jit,
            tail=self._tail_jit, decode=self._decode_jit,
            verify=self._verify_jit, zero=self._zero_jit,
            cow=self._cow_jit, npool=self._npool, W=self._W,
            loaded=self._loaded, execs=self._execs,
            tier_gather=self._tier_gather_jit,
            tier_write=self._tier_write_jit)

    def _store_key_material(self) -> dict:
        """Everything that shapes the traced programs, JSON-able — the
        content key the store directories hang off. The decode-weight
        pytree spec doubles as the quant-manifest digest (int8 leaves
        + scale rows have their own dtypes/shapes); the FLAGS listed
        are the kernel selections the compiled programs bake in."""
        import jax
        import jaxlib

        from ..jit import pytree_spec
        mcfg = self._model.gpt.config
        dev = jax.devices()[0]
        return {
            "model": {k: v for k, v in sorted(vars(mcfg).items())},
            "weights_spec": pytree_spec(self._W),
            "engine": {
                "max_slots": self._cfg.max_slots,
                "page_size": self._cfg.page_size,
                "num_pages": self._cfg.num_pages,
                "pages_per_seq": self._cfg.pages_per_seq,
                "prefill_buckets": list(self._cfg.prefill_buckets),
                "kv_dtype": self._cache.dtype,
                "quant_kv": bool(self._quant_kv),
                "use_tail": bool(self._use_tail),
                "prefix_cache": self._prefix is not None,
                "kv_tier": self._tier is not None,
                "kv_tier_chunk_pages": self._cfg.kv_tier_chunk_pages,
                "spec_k": self._spec_k,
                "top_k": self._cfg.top_k,
                # mesh-slice lane (ISSUE 19): tp degree + mesh shape
                # join the content key — a shard_map program compiled
                # for one slice layout must never resolve on another
                "tp": self._tp,
                "mesh_shape": (dict(self._mesh.shape)
                               if self._mesh is not None else None),
            },
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", "unknown"),
            "device": str(self._device) if self._device is not None
            else None,
            "flags": {
                "FLAGS_use_paged_attention":
                    bool(flag("FLAGS_use_paged_attention")),
                "FLAGS_paged_compute_block_pages":
                    int(flag("FLAGS_paged_compute_block_pages")),
                "FLAGS_flash_attention_interpret":
                    bool(flag("FLAGS_flash_attention_interpret")),
            },
        }

    def _dev_ctx(self):
        import jax
        import contextlib
        return (jax.default_device(self._device)
                if self._device is not None else contextlib.nullcontext())

    def _prog(self, name, jit_fn):
        """The program to run for `name`: the AOT executable warmup
        resolved (store-loaded or live-compiled-and-written-back) when
        present, else the jax.jit wrapper — the store-off path,
        behaviorally identical (ISSUE 16)."""
        return self._execs.get(name, jit_fn)

    def _decode_call(self, *args):
        """One jitted decode dispatch (seam: tests wrap this to inject
        per-slot failures)."""
        with self._dev_ctx():
            return self._prog(f"decode[m={self._cfg.max_slots}]",
                              self._decode_jit)(*args)

    def _verify_call(self, *args):
        """One jitted speculative-verify dispatch (same test seam
        discipline as `_decode_call`)."""
        with self._dev_ctx():
            return self._prog(f"verify[k={self._spec_k}]",
                              self._verify_jit)(*args)

    def _zero_pages(self, pages):
        # chunked to the fixed zero-scatter width: one sequence's free
        # fits a single row, but a prefix-cache eviction sweep can
        # return more pages than pages_per_seq at once
        PP = self._cfg.pages_per_seq
        for i in range(0, max(len(pages), 1), PP):
            row = self._cache.zero_rows(pages[i:i + PP])
            with self._dev_ctx():
                self._set_pools(self._zero_jit(*self._pools(), row))

    def _cow_copy(self, src: int, dst: int):
        """Device-side CoW clone of one page (content + int8 scale row)."""
        with self._dev_ctx():
            fn = self._prog("cow_copy", self._cow_jit)
            self._set_pools(fn(*self._pools(), np.int32(src),
                               np.int32(dst)))

    # -- host tier (ISSUE 18) ----------------------------------------------

    def _tier_gather_page(self, page: int):
        """Demotion gather callback (`PrefixCache.attach_tier`): one
        page's raw blocks off-device as host numpy — (k, v, ks, vs),
        scale rows None outside the int8 mode. None = gather failed
        (the `kv_tier.demote_gather` failpoint): the eviction proceeds
        plain, content discarded — the PR 12 behavior exactly."""
        if failpoints.fire("kv_tier.demote_gather") is not None:
            return None
        with self._dev_ctx():
            out = self._tier_gather_jit(*self._pools(), np.int32(page))
        if self._quant_kv:
            return tuple(np.asarray(o) for o in out)
        return (np.asarray(out[0]), np.asarray(out[1]), None, None)

    def _promote_upload(self, req: _GenRequest, host_digests,
                        matched_hbm: int) -> bool:
        """Re-upload an admission's matched host-tier run into its own
        fresh target pages (`pt_row[matched_hbm:]`), double-buffered:
        chunk i+1's `jax.device_put` staging overlaps chunk i's (async)
        tier_write dispatch, and nothing here syncs the host — the tail
        prefill queues behind the uploads on the device stream, which
        is how the promotion hides behind prefill instead of adding to
        TTFT. Returns True on success, False on abandon.

        Abandon (the `kv_tier.promote_upload` failpoint, checked BEFORE
        each chunk's donating dispatch so no pool is ever
        half-consumed): the target pages written so far are zeroed —
        content AND int8 scale grids, essential because the tail
        prefill's requant write would otherwise merge junk scales into
        a grid that only ever widens — the never-written tail is
        already zero (fresh pages arrive zeroed), and the caller falls
        back to cold-prefilling the whole suffix. The popped host
        entries are gone either way: move semantics, one copy ever."""
        import jax
        C = self._cfg.kv_tier_chunk_pages
        n = len(host_digests)
        targets = [int(p) for p in
                   req.pt_row[matched_hbm:matched_hbm + n]]
        entries, cascaded = self._prefix.consume_promoted(host_digests)
        if cascaded:
            self._audit.audit("KV_TIER_EVICT", rid=req.rid,
                              entries=cascaded)
        if any(e is None for e in entries):
            # defensive: protect() held these across the eviction pass,
            # so a missing entry is a logic fault — abandon cleanly
            # (nothing written yet) rather than upload garbage
            self._tier.note_abandon()
            self._audit.audit("KV_PROMOTE_ABANDON", rid=req.rid,
                              pages=n, written=0)
            return False

        def stage(lo: int):
            hi = min(lo + C, n)
            row = np.full((C,), TRASH_PAGE, np.int32)
            row[:hi - lo] = targets[lo:hi]
            e0 = entries[0]
            blocks = [np.zeros((C,) + e0.k.shape, e0.k.dtype),
                      np.zeros((C,) + e0.v.shape, e0.v.dtype)]
            if self._quant_kv:
                blocks += [np.zeros((C,) + e0.ks.shape, e0.ks.dtype),
                           np.zeros((C,) + e0.vs.shape, e0.vs.dtype)]
            for j in range(lo, hi):
                blocks[0][j - lo] = entries[j].k
                blocks[1][j - lo] = entries[j].v
                if self._quant_kv:
                    blocks[2][j - lo] = entries[j].ks
                    blocks[3][j - lo] = entries[j].vs
            with self._dev_ctx():
                if self._tp == 1:
                    return [jax.device_put(a) for a in [row] + blocks]
                # stage straight onto the slice: each block is a FULL
                # host page [C, L, H, ...] — split its head axis across
                # the mesh here so the donating tier_write dispatch
                # pays no reshard (the overlap this path exists for)
                from jax.sharding import NamedSharding, PartitionSpec

                def ns(a):
                    spec = [None] * a.ndim
                    spec[2] = "tp"
                    return NamedSharding(self._mesh, PartitionSpec(*spec))
                return [jax.device_put(row)] + [
                    jax.device_put(a, ns(a)) for a in blocks]

        t0 = _now_ms()
        written = 0
        staged = stage(0)
        while written < n:
            if failpoints.fire("kv_tier.promote_upload") is not None:
                self._zero_pages(targets[:written])
                self._tier.note_abandon()
                self._audit.audit("KV_PROMOTE_ABANDON", rid=req.rid,
                                  pages=n, written=written)
                # abandoned upload time still went somewhere — charge
                # the promote bucket (ISSUE 20 attribution)
                self._it["promote_ms"] += _now_ms() - t0
                return False
            nxt = stage(written + C) if written + C < n else None
            with RecordEvent(f"generation::tier_write[w={C}]"):
                with self._dev_ctx():
                    self._set_pools(self._tier_write_jit(
                        *self._pools(), *staged))
            written = min(written + C, n)
            staged = nxt
        self._tier.note_promotion(n)
        self._audit.audit("KV_PROMOTE", rid=req.rid, pages=n,
                          tokens=n * self._cfg.page_size,
                          ms=round(_now_ms() - t0, 3))
        self._it["promote_ms"] += _now_ms() - t0
        return True

    # -- program-store warmup seam (ISSUE 16) ------------------------------

    def _reset_pools(self):
        """Rebuild zeroed device pools after a failed store probe
        DONATED the live ones into a broken executable. Warmup-time
        only: at that point the pools hold nothing but scratch-page
        writes, so zeros are the correct state (shape/dtype metadata
        survives buffer deletion)."""
        import jax.numpy as jnp
        place = self._cache._place  # keeps the tp mesh placement
        self._kp = place(jnp.zeros(self._kp.shape, self._kp.dtype))
        self._vp = place(jnp.zeros(self._vp.shape, self._vp.dtype))
        if self._quant_kv:
            self._ks = place(jnp.zeros(self._ks.shape, self._ks.dtype))
            self._vs = place(jnp.zeros(self._vs.shape, self._vs.dtype))

    def _selfcheck_alias(self, compiled, recorded: str):
        """The PR 1 structural gate on a LOADED executable: its
        input/output aliasing must match the spec the live compile
        recorded at write time, and must not be empty — every covered
        program donates its pools, so an executable that aliases
        nothing is exactly the aliasing-drop corruption class (it
        would read freed buffers at the second call). Returns an error
        string, or None when the check passes."""
        from ..jit import compiled_alias_spec
        live = compiled_alias_spec(compiled)
        if live != recorded:
            return (f"alias spec mismatch: loaded={live!r} vs "
                    f"recorded={recorded!r}")
        if not live.strip():
            return ("empty alias spec on a donating program — the "
                    "PR 1 aliasing-drop corruption class")
        return None

    @staticmethod
    def _probe_ok(name: str, out) -> bool:
        """Numeric smoke verdict on one warmup execution of a loaded
        executable: prefill-family programs must return finite logits,
        decode/verify must not raise their in-graph poison flag;
        cow_copy completing `block_until_ready` is the probe (it
        returns only pools)."""
        if name.startswith("prefill"):
            return bool(np.all(np.isfinite(np.asarray(out[-1]))))
        if name.startswith(("decode", "verify")):
            return not bool(np.asarray(out[-1]).any())
        return True

    def _warm_one(self, name: str, jit_fn, args_fn):
        """Resolve + execute one warmup program, preferring the store.

        Hit → deserialize, run the donation-aliasing self-check, then
        the numeric smoke probe (ONE scratch execution — the warmup
        call itself); only then does the executable enter the pack and
        `loaded[name]` count it. Any failure bumps
        STAT_pack_selfcheck_failures, dumps a flight record, rebuilds
        the (possibly donated-away) pools, and falls through to live
        compile — a corrupt or stale entry costs a compile, never a
        wrong answer. Miss with a store → AOT lower+compile (note()
        fires at trace time, so the compile ledger counts it exactly
        as before), execute, write back. No store → the jax.jit
        wrapper traces on call: the pre-ISSUE-16 path, untouched."""
        ex = self._execs.get(name)
        if ex is not None:     # resurrection: the pack already resolved it
            return ex(*args_fn())
        if self._store is None:
            return jit_fn(*args_fn())
        hit = self._store.load(name)
        if hit is not None:
            import jax
            compiled, recorded = hit
            err = self._selfcheck_alias(compiled, recorded)
            out = None
            if err is None:
                try:
                    out = compiled(*args_fn())
                    jax.block_until_ready(out)
                    if not self._probe_ok(name, out):
                        err = "numeric smoke probe failed"
                except Exception as e:  # noqa: BLE001
                    err = f"smoke probe raised: {e!r}"
            if err is None:
                self._execs[name] = compiled
                self._loaded[name] = self._loaded.get(name, 0) + 1
                return out
            monitor.stat_add("STAT_pack_selfcheck_failures")
            flight_recorder.dump(
                "program_store_selfcheck",
                extra={"engine": self.name, "program": name,
                       "key": self._store.key, "error": err})
            self._reset_pools()
        compiled = jit_fn.lower(*args_fn()).compile()
        self._execs[name] = compiled
        self._store.store(name, compiled)
        return compiled(*args_fn())

    def _warmup(self):
        """Compile every prefill bucket + the decode step (or, with
        speculation on, the ONE verify[k] program that replaces it) +
        the zeroing scatter up front: no live request pays a compile,
        and the ledger's exactly-once invariant is observable from step
        one. Warmup writes land only in the reserved scratch page.

        With a program store (ISSUE 16), every covered program resolves
        through `_warm_one` instead: a key-matched store entry
        deserializes (self-check + smoke probe gated) and the compile
        ledger does not move — `loaded` counts it instead. A miss
        AOT-compiles and writes back, so the NEXT process warm-starts."""
        M, PP = self._cfg.max_slots, self._cfg.pages_per_seq
        trash = np.zeros((PP,), np.int32)
        with RecordEvent("generation::warmup"):
            for b in self._cfg.prefill_buckets:
                ids = np.zeros((1, b), np.int32)
                with self._dev_ctx():
                    # lint: allow(use-after-donate): donate_argnums covers only the NP pool args riding in the *splat; trash sits AFTER them (position NP+1) and is never donated — reused read-only across warmup prefills
                    out = self._warm_one(
                        f"prefill[b={b}]", self._prefill_jit,
                        lambda: (self._W, *self._pools(), trash, ids,
                                 np.int32(1)))
                self._set_pools(out[:-1])
                np.asarray(out[-1])
                if self._use_tail:
                    # one tail-prefill compile per bucket too: prefix
                    # hits AND prefill chunks ride these programs, and
                    # neither may pay a runtime compile — the ledger's
                    # exactly-once invariant covers both prefill shapes
                    # from step one
                    with self._dev_ctx():
                        # lint: allow(use-after-donate): donate covers only the NP pool args in the *splat; trash/ids ride AFTER them (positions NP+1/NP+2), read-only across warmup prefills
                        out = self._warm_one(
                            f"prefill_tail[b={b}]", self._tail_jit,
                            lambda: (self._W, *self._pools(), trash, ids,  # lint: allow(use-after-donate): same — non-donated arg positions, reused read-only
                                     np.int32(1), np.int32(0)))
                    self._set_pools(out[:-1])
                    np.asarray(out[-1])
            if self._prefix is not None:
                with self._dev_ctx():
                    out = self._warm_one(
                        "cow_copy", self._cow_jit,
                        lambda: (*self._pools(), np.int32(TRASH_PAGE),
                                 np.int32(TRASH_PAGE)))
                self._set_pools(out)
            if self._tier is not None:
                # tier programs (ISSUE 18) warm OUTSIDE the program
                # store: tier_gather keeps its pools (non-donating —
                # it copies a page out), so it can never satisfy the
                # store's every-covered-program-donates aliasing
                # self-check; both compile live against the jit
                # wrappers instead (the wrappers ride the pack, so a
                # supervised restart still re-warms from cache with
                # zero new traces)
                with self._dev_ctx():
                    g = self._tier_gather_jit(*self._pools(),
                                              np.int32(TRASH_PAGE))
                blocks = [np.asarray(b) for b in g]
                C = self._cfg.kv_tier_chunk_pages
                row = np.full((C,), TRASH_PAGE, np.int32)
                args = [row] + [np.zeros((C,) + b.shape, b.dtype)
                                for b in blocks]
                with self._dev_ctx():
                    # lint: allow(use-after-donate): donate covers only the NP pool args in the *splat; row/blocks ride AFTER them, read-only
                    self._set_pools(self._tier_write_jit(*self._pools(),
                                                         *args))
            if self._spec_k:
                # speculation replaces the decode program outright: the
                # engine's ledger shows ONE verify[k] trace and no
                # decode entry at all (the acceptance-criteria shape)
                vargs = self._spec_arrays()[0]
                with self._dev_ctx():
                    out = self._warm_one(
                        f"verify[k={self._spec_k}]", self._verify_jit,
                        lambda: (self._W, *self._pools(), *vargs))
                np.asarray(out[-2])
                self._set_pools(out[:-3])
                if self._poison_degrade_k or self._degraded_spec_off:
                    # the poison-storm detector (ISSUE 15) may flip this
                    # engine to the plain decode program mid-flight —
                    # pre-warm it so the DEGRADED_SPEC_OFF flip mints no
                    # runtime compile (the ledger then shows BOTH
                    # verify[k] and decode[m], each exactly once)
                    dargs = self._step_arrays()
                    with self._dev_ctx():
                        out = self._warm_one(
                            f"decode[m={M}]", self._decode_jit,
                            lambda: (self._W, *self._pools(), *dargs))
                    np.asarray(out[-2])
                    self._set_pools(out[:-2])
            else:
                dargs = self._step_arrays()
                with self._dev_ctx():
                    out = self._warm_one(
                        f"decode[m={M}]", self._decode_jit,
                        lambda: (self._W, *self._pools(), *dargs))
                np.asarray(out[-2])
                self._set_pools(out[:-2])
            self._zero_pages([])

    # -- request intake ----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               timeout_ms: Optional[float] = None,
               do_sample: bool = False,
               temperature: float = 1.0,
               trace_id: Optional[str] = None) -> Future:
        """Enqueue one prompt (1-D int token ids); returns a Future of
        the full sequence (prompt + generated tokens, numpy int32; EOS,
        when hit, is included). Raises `EngineOverloaded` at
        max_queue_depth, `InvalidArgumentError`/`ResourceExhaustedError`
        for requests that could never run. `trace_id` is an upstream
        hop's fleet trace id (ISSUE 20) — omitted, the engine mints its
        own when FLAGS_trace_propagation is on."""
        return self._submit(prompt_ids, max_new_tokens, eos_token_id,
                            timeout_ms, do_sample, temperature,
                            stream=None, ttft_timeout_ms=None,
                            trace_id=trace_id).future

    def submit_stream(self, prompt_ids,
                      max_new_tokens: Optional[int] = None,
                      eos_token_id: Optional[int] = None,
                      timeout_ms: Optional[float] = None,
                      ttft_timeout_ms: Optional[float] = None,
                      do_sample: bool = False,
                      temperature: float = 1.0,
                      trace_id: Optional[str] = None) -> TokenStream:
        """Streaming submit: tokens leave the engine as they are
        decoded. Returns a `TokenStream` — iterate it for per-token
        delivery (each token lands after its iteration's step-ring
        record; the final token always precedes the future's
        resolution), `stream.result()` for the full sequence.

        Deadline semantics split for streams (ISSUE 12):
        `ttft_timeout_ms` is HARD — expiry before the first token
        cancels the request with `ExecutionTimeoutError` (a stream that
        cannot start on time is useless). `timeout_ms` is SOFT once
        tokens flow — expiry mid-stream stops decoding, frees the
        pages, and resolves the stream AND future with the tokens
        already delivered (they left the engine; there is nothing to
        retract), counted as a timeout for SLO purposes."""
        if ttft_timeout_ms is not None and float(ttft_timeout_ms) < 0:
            raise InvalidArgumentError("ttft_timeout_ms must be >= 0")
        stream = TokenStream(Future())
        self._submit(prompt_ids, max_new_tokens, eos_token_id,
                     timeout_ms, do_sample, temperature,
                     stream=stream, ttft_timeout_ms=ttft_timeout_ms,
                     trace_id=trace_id)
        return stream

    def _submit(self, prompt_ids, max_new_tokens, eos_token_id,
                timeout_ms, do_sample, temperature, stream,
                ttft_timeout_ms, trace_id=None) -> _GenRequest:
        from . import EngineOverloaded
        with RecordEvent("generation::submit"):
            from ..framework.tensor import Tensor
            if isinstance(prompt_ids, Tensor):
                prompt_ids = prompt_ids.numpy()
            prompt = np.asarray(prompt_ids)
            if prompt.ndim != 1 or prompt.size < 1:
                raise InvalidArgumentError(
                    f"{self.name}: prompt_ids must be a non-empty 1-D "
                    f"token array, got shape {tuple(prompt.shape)}")
            if not np.issubdtype(prompt.dtype, np.integer):
                raise InvalidArgumentError(
                    f"{self.name}: prompt_ids must be integer token ids")
            prompt = prompt.astype(np.int32)
            max_new = int(self._cfg.max_new_tokens
                          if max_new_tokens is None else max_new_tokens)
            if max_new < 1:
                raise InvalidArgumentError("max_new_tokens must be >= 1")
            S = int(prompt.size)
            total = S + max_new
            if S > self._cfg.prefill_buckets[-1]:
                raise InvalidArgumentError(
                    f"{self.name}: prompt length {S} exceeds the largest "
                    f"prefill bucket {self._cfg.prefill_buckets[-1]}")
            if total > self._max_position:
                raise InvalidArgumentError(
                    f"{self.name}: {total} positions exceed "
                    f"max_position_embeddings={self._max_position}")
            if not self._cache.fits(total):
                raise ResourceExhaustedError(
                    f"{self.name}: {total} tokens need "
                    f"{self._cache.pages_needed(total)} pages but the "
                    f"pool holds {self._cache.usable_pages} "
                    f"(pages_per_seq={self._cache.pages_per_seq}); raise "
                    f"FLAGS_paged_num_pages or shrink the request")
            if self._admit_clamped and not self._cache.can_admit(total):
                # degraded admission clamp (ISSUE 15): the allocator
                # has been exhausted repeatedly — a request the pool
                # cannot cover RIGHT NOW would only queue toward a
                # timeout, so shed it fast with a typed error
                monitor.stat_add("STAT_gen_rejected")
                raise ResourceExhaustedError(
                    f"{self.name}: admission clamped after repeated "
                    f"allocator exhaustion "
                    f"(FLAGS_gen_exhaust_clamp_k) and the pool cannot "
                    f"cover {total} tokens now; retry later or shrink "
                    f"the request")
            t = _now_ms()
            tmo = (self._cfg.request_timeout_ms if timeout_ms is None
                   else float(timeout_ms))
            ttft_tmo = (0.0 if ttft_timeout_ms is None
                        else float(ttft_timeout_ms))
            # fleet trace context (ISSUE 20): an upstream hop (the
            # Router) supplies the id — the chain was opened there, so
            # the span emits a flow STEP; a direct submit mints locally
            # (chain root) when propagation is on; off = no id, no cost
            tid, trace_root = None, True
            if trace_id is not None and trace_context.is_trace_id(
                    str(trace_id)):
                tid, trace_root = str(trace_id), False
            elif trace_context.enabled():
                tid = trace_context.new_trace_id()
            reject_depth = None
            with self._cv:
                if self._closed:
                    raise UnavailableError(
                        f"{self.name}: engine is shut down")
                if len(self._queue) >= self._cfg.max_queue_depth:
                    reject_depth = len(self._queue)
                else:
                    req = _GenRequest(
                        prompt, max_new, eos_token_id, bool(do_sample),
                        float(temperature),
                        stream.future if stream is not None else Future(),
                        None if not tmo else t + tmo, t,
                        spans.start_gen(self.name,
                                        incarnation=self.incarnation,
                                        trace_id=tid,
                                        trace_root=trace_root),
                        stream=stream,
                        ttft_deadline_ms=(t + ttft_tmo if ttft_tmo
                                          else None),
                        trace_id=tid)
                    if stream is not None:
                        stream.trace_id = tid
                    self._req_seq += 1
                    req.ordinal = self._req_seq
                    self._queue.append(req)
                    monitor.stat_add("STAT_gen_queue_depth")
                    self._cv.notify_all()
            if reject_depth is not None:
                # audited OUTSIDE the lock: the JSONL sink's disk write
                # must not stall the step thread behind rejecting
                # clients, and rejections spike exactly under overload
                monitor.stat_add("STAT_gen_rejected")
                self._audit.audit("REJECT_QUEUE_FULL",
                                  queue_depth=reject_depth)
                self._audit.flush_sink()
                raise EngineOverloaded(
                    f"{self.name}: queue depth "
                    f"{self._cfg.max_queue_depth} reached; shed load "
                    f"or raise FLAGS_gen_max_queue_depth")
            monitor.stat_add("STAT_gen_requests")
            return req

    def generate(self, prompt_ids, **kw) -> np.ndarray:
        """Synchronous submit: blocks for this prompt's full sequence."""
        return self.submit(prompt_ids, **kw).result()

    def replay_submit(self, entry: ReplayEntry, prompt: np.ndarray,
                      max_new: int, skip_stream: int = 0) -> None:
        """Re-enqueue a crash-manifest entry on THIS (rebuilt) engine
        (ISSUE 15, the supervisor seam). The caller-held future and
        stream are preserved verbatim; `prompt`/`max_new` are the
        supervisor's continuation (prompt + generated-so-far, remaining
        budget) or the original pair for a from-scratch replay, where
        `skip_stream` suppresses re-delivery of already-streamed greedy
        tokens. Deadlines carry over unchanged — a replay never buys a
        request more time. Bypasses the queue-depth bound: the request
        was admitted once already and must not be shed by the very
        restart that interrupted it."""
        prompt = np.asarray(prompt, np.int32)
        with self._cv:
            if self._closed:
                raise UnavailableError(
                    f"{self.name}: engine is shut down")
            # the hard TTFT deadline applies to the FIRST token ever
            # delivered, and an entry that generated anything met it in
            # a previous incarnation — carrying the (likely elapsed)
            # deadline onto the replay would expire a request the
            # caller already saw streaming (the whole-request deadline
            # still carries over unchanged)
            ttft = (entry.ttft_deadline_ms
                    if not entry.toks and not entry.delivered else None)
            req = _GenRequest(
                prompt, int(max_new), entry.eos, entry.do_sample,
                entry.temperature, entry.future, entry.deadline_ms,
                entry.t_enqueue_ms,
                spans.start_gen(self.name,
                                incarnation=self.incarnation,
                                trace_id=entry.trace_id,
                                trace_root=False),
                stream=entry.stream,
                ttft_deadline_ms=ttft,
                trace_id=entry.trace_id)
            req.claimed = entry.claimed
            req.retries = entry.retries + 1
            req.skip_stream = int(skip_stream)
            self._req_seq += 1
            req.ordinal = self._req_seq
            self._queue.append(req)
            monitor.stat_add("STAT_gen_queue_depth")
            self._cv.notify_all()
        monitor.stat_add("STAT_gen_replayed_requests")
        self._audit.audit(
            "REPLAY_ADMIT", rid=req.rid, orig_rid=entry.rid,
            retries=req.retries, generated=len(entry.toks),
            continuation=int(prompt.size) > int(entry.prompt.size),
            skip_stream=int(skip_stream),
            **({"trace": entry.trace_id} if entry.trace_id else {}))

    # -- step loop ---------------------------------------------------------

    def _num_active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    def _loop(self):
        # goodput-attribution marks (ISSUE 20): `t_mark` is the previous
        # iteration's record boundary — wall is mark-to-mark, so the
        # record/flush bookkeeping AFTER a record lands is charged to
        # the NEXT iteration's bookkeeping bucket and consecutive
        # buckets still tile the step thread's timeline exactly
        t_mark = time.perf_counter()
        idle_s = 0.0
        try:
            while True:
                with self._cv:
                    while (not self._queue and self._num_active() == 0
                           and not self._closed):
                        t0 = time.perf_counter()
                        self._cv.wait()
                        idle_s += time.perf_counter() - t0
                    if self._closed and self._abort:
                        self._evict_all(UnavailableError(
                            f"{self.name}: engine shut down"))
                        # flush the aborted/freed counts: the ring's
                        # sums must reconcile even on the abort exit
                        # (self._cv is an RLock-backed Condition, so
                        # re-acquiring inside is fine)
                        self._record_iteration()
                        self._flush_resolutions()
                        return
                    if (self._closed and not self._queue
                            and self._num_active() == 0):
                        return
                t0 = time.perf_counter()
                self._admit()
                self._expire_active()
                if self._cfg.prefill_chunk:
                    self._advance_prefills()
                sched_s = time.perf_counter() - t0
                stepped = False
                if any(r is not None and r.prefill_pos is None
                       for r in self._slots):
                    self._step()
                    stepped = True
                now = time.perf_counter()
                it = self._it
                it["attr_idle_ms"] = idle_s * 1000.0
                it["attr_sched_ms"] = sched_s * 1000.0
                it["attr_wall_ms"] = (now - t_mark) * 1000.0
                t_mark, idle_s = now, 0.0
                self._record_iteration()
                # sink before resolutions: a caller woken by result()
                # may immediately read the JSONL — its own event must
                # already be on disk (no lock held here)
                self._audit.flush_sink()
                self._flush_resolutions()
                if not stepped:
                    with self._cv:
                        if (self._queue and self._num_active() == 0
                                and not self._abort):
                            # unadmittable head (page exhaustion): bounded
                            # wait so queued deadlines still expire
                            t0 = time.perf_counter()
                            self._cv.wait(0.01)
                            idle_s += time.perf_counter() - t0
        except BaseException as e:  # noqa: BLE001 — never hang submitters
            if self._die(e):
                return  # supervised: the death was handed over and
                #         handled — no stderr traceback for a recovery
                #         that worked
            raise

    def _record_iteration(self):
        """One compact scheduler record per engine iteration (ISSUE 11):
        decision counts taken this pass, queue pressure, page-pool
        occupancy, prefill-vs-decode wall. Pure host bookkeeping — one
        ring append plus two histogram observes, no device syncs beyond
        what the iteration already did. The per-iteration counter dict
        is zeroed whether or not the ring is on, so an A/B flag flip
        can't leak one arm's counts into the other."""
        it, self._it = self._it, {
            "admitted": 0, "completed": 0, "expired": 0, "poisoned": 0,
            "aborted": 0, "freed": 0, "prefix_tokens": 0,
            "cow_splits": 0, "tokens": 0, "spec_drafted": 0,
            "spec_accepted": 0, "prefill_chunks": 0,
            "prefill_ms": 0.0, "decode_ms": 0.0,
            "promote_ms": 0.0,
            "attr_idle_ms": 0.0, "attr_sched_ms": 0.0,
            "attr_wall_ms": 0.0}
        # pressure snapshot (ISSUE 17): republished every iteration on
        # the step thread — the only thread that mutates the allocator —
        # so `pressure()` readers never need the engine lock. Runs even
        # with the step ring off: the router polls regardless.
        self._pressure = self._compute_pressure()
        if self._step_log is None:
            return
        self._iters += 1
        with self._cv:
            depth = len(self._queue)
            oldest = (self._queue[0].t_enqueue_ms if self._queue
                      else None)
            live = self._num_active()
        # host-tier activity this iteration (ISSUE 18): deltas of the
        # tier's cumulative counters — one bookkeeping path, no second
        # per-iteration dict to zero
        tier_dem = tier_pro = 0
        if self._tier is not None:
            d, p = self._tier.demotions, self._tier.promotions
            ld, lp = self._tier_counts
            tier_dem, tier_pro = d - ld, p - lp
            self._tier_counts = (d, p)
        # goodput attribution (ISSUE 20): six buckets that reconcile
        # EXACTLY to the iteration wall. Every stored value is rounded
        # first and bookkeeping is the remainder OF THE ROUNDED parts,
        # so `/steps` readers can assert the sum without fp slack from
        # our side. The admit bucket is the scheduler-gross time minus
        # the prefill/promote device work nested inside it; bookkeeping
        # absorbs decode-side host work beyond the device call plus the
        # previous iteration's record/flush tail (mark-to-mark wall).
        a_wall = round(it["attr_wall_ms"], 3)
        a_idle = round(it["attr_idle_ms"], 3)
        a_prefill = round(it["prefill_ms"], 3)
        a_promote = round(it["promote_ms"], 3)
        a_decode = round(it["decode_ms"], 3)
        a_admit = round(max(0.0, it["attr_sched_ms"]
                            - it["prefill_ms"] - it["promote_ms"]), 3)
        a_book = (a_wall - a_idle - a_admit - a_prefill - a_promote
                  - a_decode)
        rec = step_log.StepRecord(
            it=self._iters, step=self._steps_total,
            t=time.perf_counter(), live=live,
            queue_depth=depth,
            oldest_age_ms=round(_now_ms() - oldest, 3)
            if oldest is not None else 0.0,
            pages_in_use=self._cache.pages_in_use,
            free_pages=self._cache.free_pages,
            admitted=it["admitted"], completed=it["completed"],
            expired=it["expired"], poisoned=it["poisoned"],
            aborted=it["aborted"], freed=it["freed"],
            prefix_tokens=it["prefix_tokens"],
            cow_splits=it["cow_splits"],
            tokens=it["tokens"],
            spec_drafted=it["spec_drafted"],
            spec_accepted=it["spec_accepted"],
            prefill_chunks=it["prefill_chunks"],
            prefill_ms=a_prefill,
            decode_ms=a_decode,
            incarnation=self.incarnation,
            tier_demotions=tier_dem, tier_promotions=tier_pro,
            tp=self._tp,
            attr_admit_ms=a_admit, attr_promote_ms=a_promote,
            attr_bookkeep_ms=a_book, attr_idle_ms=a_idle,
            attr_wall_ms=a_wall)
        self._step_log.record(rec)

    def _resolve_later(self, req: Optional[_GenRequest], fut,
                       result=None, exc=None):
        """Hold a future's resolution until after this iteration's
        _record_iteration(): a caller woken by result() must observe a
        step ring / audit tail that already includes its own outcome —
        resolving mid-iteration let a reader hit /steps before the
        record landed and see counts that don't reconcile. `req` rides
        along so _die can dedupe by rid: a request with a staged
        outcome must never ALSO receive the death error."""
        self._resolve_q.append((req, fut, result, exc))

    def _resolve_req_later(self, req: _GenRequest, result=None, exc=None):
        """Request-level resolution: the stream (when present) gets its
        terminal marker — the error, or the end-of-stream sentinel —
        staged BEFORE the future, behind the same barrier."""
        if req.stream is not None:
            self._stream_q.append((req.stream,
                                   exc if exc is not None
                                   else TokenStream._END))
        self._resolve_later(req, req.future, result, exc)

    def _stage_token(self, req: _GenRequest, tok: int):
        """Stage one decoded token for post-barrier stream delivery.
        A from-scratch greedy replay (ISSUE 15) suppresses the first
        `skip_stream` tokens — they were already delivered by the
        previous incarnation, and greedy re-derivation makes them
        byte-identical, so suppression preserves exactly-once."""
        if req.stream is None:
            return
        if req.skip_stream > 0:
            req.skip_stream -= 1
            return
        self._stream_q.append((req.stream, tok))

    def _flush_resolutions(self):
        # streams first: a stream's final token / terminal marker must
        # be readable by the time its future resolves ("streamed tokens
        # arrive before resolved")
        sq, self._stream_q = self._stream_q, []
        for stream, item in sq:
            stream._put(item)
        q, self._resolve_q = self._resolve_q, []
        for _req, fut, result, exc in q:
            try:
                if exc is not None:
                    fut.set_exception(exc)
                else:
                    fut.set_result(result)
            except Exception:  # lint: allow(except-pass): racing caller-side cancel pre-admission — the future is already settled, there is nothing left to deliver
                pass

    def _die(self, e: BaseException):
        # two INDEPENDENT try blocks: a ring-record failure on a
        # half-broken engine must not also strand the staged
        # resolutions (they carry real results/errors already decided)
        try:
            # flush whatever the dying iteration already counted, so
            # the dump's step_log_tail reconciles with the audit tail
            self._record_iteration()
        except Exception:  # lint: allow(except-pass): best-effort ring record on a dying engine — the death path must keep going
            pass
        # settled BEFORE the flush: these requests already have an
        # outcome staged this iteration — after the flush delivers it,
        # the death error below must never reach them too (a request
        # observing BOTH a result and the death error was the ISSUE 15
        # resolution race)
        settled = {req.rid for req, _f, _r, _e in self._resolve_q
                   if req is not None}
        try:
            self._flush_resolutions()
        except Exception:  # lint: allow(except-pass): best-effort flush on a dying engine — per-future failures are already guarded inside
            pass
        stranded = []
        with self._cv:
            self._closed = True
            self._death = e
            while self._queue:
                req = self._queue.popleft()
                monitor.stat_sub("STAT_gen_queue_depth")
                if req.rid not in settled:
                    stranded.append(req)
            self._cv.notify_all()
        active = [r for r in self._slots
                  if r is not None and r.rid not in settled]
        if self._on_death is not None:
            # supervised (ISSUE 15): hand the queued + live work to the
            # supervisor as a crash manifest instead of stranding it —
            # the supervisor rebuilds the engine and replays
            manifest = CrashManifest(
                engine=self.name, incarnation=self.incarnation,
                error=e,
                entries=([ReplayEntry(r, queued=False)
                          for r in sorted(active,
                                          key=lambda r: r.ordinal)]
                         + [ReplayEntry(r, queued=True)
                            for r in stranded]),
                degraded_spec_off=self._degraded_spec_off,
                kv=self._cache.manifest(), compiles=dict(self._ledger))
            self._audit.flush_sink()
            flight_recorder.dump("gen_engine_death", {
                "engine": self.name, "error": repr(e),
                "supervised": True,
                "manifest": manifest.summary(),
                "inflight_spans": [r.span.to_dict() for r in active
                                   if r.span is not None][:64],
                "step_log_tail": (self._step_log.tail(32)
                                  if self._step_log is not None else []),
                "audit_tail": self._audit.tail(64)})
            try:
                self._on_death(manifest)
                return True
            except Exception as sup_e:  # supervisor itself failed:
                #                         fall through and strand typed
                #                         rather than hang the callers
                e = RuntimeError(
                    f"supervisor failed during restart: {sup_e!r} "
                    f"(original death: {e!r})")
        err = UnavailableError(f"{self.name}: generation engine died: "
                               f"{e!r}")
        for req in active + stranded:
            if req.stream is not None:
                # direct put (no barrier): the step loop is dead, no
                # further _flush_resolutions will run
                req.stream._put(err)
            try:
                req.future.set_exception(err)
            except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
                pass
            self._audit.audit("ENGINE_DIED", rid=req.rid,
                              error=repr(e))
            slo.observe_request(self.name, ok=False)
        self._audit.flush_sink()
        flight_recorder.dump("gen_engine_death", {
            "engine": self.name, "error": repr(e),
            "stranded_requests": len(stranded),
            "active_sequences": len(active),
            "inflight_spans": [r.span.to_dict() for r in active
                               if r.span is not None][:64],
            # the scheduler state that LED here: last step-ring records
            # + the decision-audit tail with reason codes (ISSUE 11)
            "step_log_tail": (self._step_log.tail(32)
                              if self._step_log is not None else []),
            "audit_tail": self._audit.tail(64)})
        return False

    # -- admission ---------------------------------------------------------

    def _admit(self):
        """Admit queued requests while a slot AND worst-case pages are
        both free (FIFO, head-of-line blocking — later smaller requests
        never overtake, so admission latency stays predictable)."""
        while True:
            with self._cv:
                # whole-queue sweep, not just the head: a request queued
                # BEHIND a page-blocked head must still get its deadline
                # error on time (head-of-line blocking blocks admission,
                # never expiry)
                self._expire_queued()
                if not self._queue:
                    return
                req = self._queue[0]
                slot = next((i for i, r in enumerate(self._slots)
                             if r is None), None)
                if slot is None:
                    # once per request per cause: a full batch defers
                    # the head every iteration, and a per-iteration
                    # event would drown the audit ring in repeats
                    if "slots" not in req.defer_logged:
                        req.defer_logged.add("slots")
                        self._audit.audit(
                            "DEFER_SLOTS", rid=req.rid,
                            queue_depth=len(self._queue))
                    return
                S = int(req.prompt.size)
                total = S + req.max_new
                need = self._cache.pages_needed(total)
                # prefix plan (ISSUE 12): the longest cached chain this
                # prompt walks maps read-only; a FULL-prompt match keeps
                # every page but must recompute its last position's
                # logits, so the page holding position S-1 is CoW-split
                # (the one divergent write) — tail length stays >= 1
                # either way, there is always a token to prefill
                digests, hit_pages, host_digests = [], [], []
                if self._prefix is not None:
                    if self._tier is not None:
                        digests, hit_pages, host_digests = \
                            self._prefix.lookup_tiered(req.prompt)
                    else:
                        digests, hit_pages = self._prefix.lookup(
                            req.prompt)
                matched_hbm = len(hit_pages)
                promote_n = len(host_digests)
                matched = matched_hbm + promote_n
                full_match = (matched > 0
                              and matched * self._cfg.page_size == S)
                # a full match whose tail comes up from the host tier
                # needs NO CoW: position S-1's recompute writes into
                # the LAST promoted page, which is this request's own
                # fresh target — private until register() re-indexes it
                cow_needed = full_match and promote_n == 0
                # promotion targets are fresh pages too, so the
                # admission arithmetic counts in-flight promotions
                # naturally: (need - matched) suffix pages + promote_n
                # targets = need - matched_hbm
                fresh_needed = (need - matched_hbm
                                + (1 if cow_needed else 0))
                pinned = bool(matched_hbm)
                if pinned:
                    # hold the matched chain across the eviction pass:
                    # refcount >= 2 takes its pages out of the
                    # evictable set, so the eviction below can never
                    # reclaim the very pages this admission maps
                    self._cache.pin(hit_pages)
                if promote_n:
                    # the SAME eviction pass may demote victims INTO
                    # the tier — shield the matched host run from its
                    # LRU until the promotion consumes it
                    self._prefix.protect(host_digests)
                try:
                    # alloc_exhaust failpoint: force the exhaustion
                    # verdict without draining the pool — the DEFER /
                    # clamp machinery downstream runs unchanged
                    if (fresh_needed > self._cache.reclaimable_pages
                            or failpoints.fire("alloc_exhaust")
                            is not None):
                        monitor.stat_add("STAT_gen_admit_blocked")
                        # every blocked ITERATION counts toward the
                        # clamp detector (head-of-line blocking means
                        # only the head defers — a per-request count
                        # would see one event per episode)
                        self._note_exhaust()
                        if "pages" not in req.defer_logged:
                            req.defer_logged.add("pages")
                            self._audit.audit(
                                "DEFER_PAGES", rid=req.rid,
                                need_pages=fresh_needed,
                                free_pages=self._cache.free_pages,
                                reclaimable=self._cache
                                .reclaimable_pages)
                        if not self._exhaust_dumped:
                            self._exhaust_dumped = True
                            flight_recorder.dump(
                                "gen_allocator_exhausted", {
                                    "engine": self.name, "rid": req.rid,
                                    "need_pages": fresh_needed,
                                    "cache": self._cache.stats(),
                                    "queue_depth": len(self._queue),
                                    "step_log_tail":
                                        (self._step_log.tail(32)
                                         if self._step_log is not None
                                         else []),
                                    "audit_tail": self._audit.tail(64)})
                        return
                    if fresh_needed > self._cache.free_pages:
                        # evictable pages counted as admission capacity
                        # above; reclaim them NOW, before alloc — the
                        # deferred zero-on-free point for cached chains
                        # (the pinned matched chain is never victimized)
                        freed = self._prefix.evict(
                            fresh_needed - self._cache.free_pages,
                            exclude=hit_pages)
                        self._audit.audit(
                            "EVICT_PREFIX_LRU", rid=req.rid,
                            pages=len(freed),
                            free_pages=self._cache.free_pages)
                        if freed:
                            self._zero_pages(freed)
                        if fresh_needed > self._cache.free_pages:
                            # under-delivery (every remaining chain is
                            # live-shared or excluded): defer rather
                            # than let alloc raise into engine death —
                            # pages reclaim through those sequences'
                            # frees
                            monitor.stat_add("STAT_gen_admit_blocked")
                            return
                    self._queue.popleft()
                    monitor.stat_sub("STAT_gen_queue_depth")
                    if not req.claimed:
                        # a REPLAYED request's future is already in the
                        # RUNNING state from its first admission — a
                        # second set_running_or_notify_cancel would
                        # raise InvalidStateError (ISSUE 15)
                        if not req.future.set_running_or_notify_cancel():
                            self._audit.audit("CANCELLED", rid=req.rid)
                            if req.stream is not None:
                                from concurrent.futures import \
                                    CancelledError
                                self._stream_q.append(
                                    (req.stream, CancelledError()))
                            continue
                        req.claimed = True
                    req.slot = slot
                    req.pt_row = self._cache.alloc_shared(
                        req.rid, total, hit_pages)
                finally:
                    if pinned:
                        self._cache.unpin(hit_pages)
                    if promote_n:
                        self._prefix.unprotect()
                cow_src = cow_dst = None
                if cow_needed:
                    cow_src = hit_pages[-1]
                    cow_dst = self._cache.cow_split(req.rid, cow_src)
                    req.pt_row[matched - 1] = cow_dst
                    monitor.stat_add("STAT_cow_splits")
                    self._it["cow_splits"] += 1
                    self._audit.audit("COW_SPLIT", rid=req.rid,
                                      src_page=cow_src, dst_page=cow_dst)
                self._slots[slot] = req
                self._it["admitted"] += 1
                if self._admit_clamped:
                    # the pool covered an admission again: the
                    # exhaustion episode is over, lift the clamp
                    self._admit_clamped = False
                    self._exhaust_times.clear()
            if cow_dst is not None:
                # clone the shared page (content + int8 scale row)
                # before the tail prefill writes position S-1 through
                # the private copy; the shared original is never
                # written under its other readers
                self._cow_copy(cow_src, cow_dst)
            if promote_n:
                # host-tier promotion (ISSUE 18) — outside the lock
                # like the CoW clone: device traffic must not stall
                # submitters. On abandon the match shrinks back to the
                # HBM run and the tail prefill covers the rest cold.
                if not self._promote_upload(req, host_digests,
                                            matched_hbm):
                    matched, full_match = matched_hbm, False
            # the admission accounting lands AFTER the promotion
            # resolved (step-thread-local state — safe off the lock):
            # an abandon must not count host pages it never served
            req.prefix_tokens = ((S - 1) if full_match
                                 else matched * self._cfg.page_size)
            if self._prefix is not None:
                self._prefix.note_admitted(
                    req.prefix_tokens,
                    host_tokens=((matched - matched_hbm)
                                 * self._cfg.page_size))
            self._it["prefix_tokens"] += req.prefix_tokens
            if matched:
                self._audit.audit(
                    "ADMIT_PREFIX_HIT", rid=req.rid, slot=slot,
                    pages=need, shared_pages=matched_hbm,
                    promoted_pages=matched - matched_hbm,
                    prefix_tokens=req.prefix_tokens,
                    queued_ms=round(_now_ms() - req.t_enqueue_ms, 3))
            else:
                self._audit.audit(
                    "ADMIT", rid=req.rid, slot=slot, pages=need,
                    queued_ms=round(_now_ms() - req.t_enqueue_ms, 3))
            if req.span is not None:
                req.span.slot = slot
                req.span.prefix_tokens = req.prefix_tokens
                req.span.stamp("admitted")
            chunk = self._cfg.prefill_chunk
            if chunk and S - req.prefix_tokens > chunk:
                # chunked prefill (ISSUE 14): the slot is admitted NOW
                # (pages reserved, FIFO order kept) but prefills one
                # chunk per engine iteration through the tail programs,
                # interleaved with decode steps — a long prompt stops
                # spiking every live sequence's TPOT. The slot joins
                # decode only when prefill_pos reaches the prompt end.
                req.prefill_pos = req.prefix_tokens
                req.pending_digests = digests
            else:
                self._do_prefill(req, digests)

    def _expire_queued(self):
        """Fail every expired request and drop every cancelled one from
        the WHOLE queue (position-independent); caller holds the lock.
        While queued nothing has been delivered, so BOTH stream
        deadlines are hard here: the TTFT deadline (first token cannot
        arrive on time) and the whole-request deadline alike."""
        t = _now_ms()
        live = deque()
        for req in self._queue:
            deadlines = [d for d in (req.deadline_ms,
                                     req.ttft_deadline_ms)
                         if d is not None]
            if deadlines and t > min(deadlines):
                monitor.stat_sub("STAT_gen_queue_depth")
                monitor.stat_add("STAT_gen_timeouts")
                self._it["expired"] += 1
                self._audit.audit(
                    "EXPIRE_QUEUED", rid=req.rid,
                    queued_ms=round(t - req.t_enqueue_ms, 3))
                slo.observe_request(self.name, ok=False)
                self._resolve_req_later(req, exc=ExecutionTimeoutError(
                    f"{self.name}: request expired after "
                    f"{t - req.t_enqueue_ms:.1f}ms in queue"))
                continue
            if req.future.cancelled():
                monitor.stat_sub("STAT_gen_queue_depth")
                self._audit.audit("CANCELLED", rid=req.rid)
                if req.stream is not None:
                    from concurrent.futures import CancelledError
                    self._stream_q.append((req.stream, CancelledError()))
                continue
            live.append(req)
        self._queue = live

    def _bucket_for(self, S: int) -> int:
        for b in self._cfg.prefill_buckets:
            if b >= S:
                return b
        return self._cfg.prefill_buckets[-1]

    def _do_prefill(self, req: _GenRequest, digests=None):
        """Run the request's prompt through the bucketed prefill program
        (writes its K/V pages), sample the first token, and mark the
        slot live — it joins the very next decode step. A prefix hit
        (req.prefix_tokens > 0) prefills ONLY the tail through the
        per-bucket tail program — the cached pages are read, never
        written. A poisoned request (non-finite logits — the pools came
        back valid) fails ONLY this request and returns its pages
        zeroed; an exception from the jitted call itself is
        engine-fatal, because the pools were DONATED into it and may
        already be consumed — touching them again (even to zero this
        request's pages) would dereference deleted buffers (same
        contract as a decode-step exception)."""
        failpoints.maybe_raise("prefill_raise")  # engine-fatal, like a
        #                                          real prefill jit error
        S = int(req.prompt.size)
        pfx = req.prefix_tokens
        tail = S - pfx
        t0 = _now_ms()
        if pfx:
            bucket = self._bucket_for(tail)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :tail] = req.prompt[pfx:]
            with RecordEvent(f"generation::prefill_tail[b={bucket}]"):
                with self._dev_ctx():
                    out = self._prog(f"prefill_tail[b={bucket}]",
                                     self._tail_jit)(
                        self._W, *self._pools(), req.pt_row, ids,
                        np.int32(tail), np.int32(pfx))
                self._set_pools(out[:-1])
                lg = np.asarray(out[-1])
        else:
            bucket = self._bucket_for(S)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :S] = req.prompt
            with RecordEvent(f"generation::prefill[b={bucket}]"):
                with self._dev_ctx():
                    out = self._prog(f"prefill[b={bucket}]",
                                     self._prefill_jit)(
                        self._W, *self._pools(), req.pt_row, ids,
                        np.int32(S))
                self._set_pools(out[:-1])
                lg = np.asarray(out[-1])
        self._it["prefill_ms"] += _now_ms() - t0
        if not np.all(np.isfinite(lg)):
            self._poison_prefill(req, bucket)
            return
        self._finish_prefill(req, lg, digests)

    def _inject_poison(self, bad: np.ndarray) -> np.ndarray:
        """`decode_poison_nan` failpoint: mark the first live slot's
        logits non-finite host-side — the exact verdict the decode
        program's in-graph isfinite check would have returned, so the
        whole poison-isolation path downstream is exercised unchanged."""
        bad = np.array(bad, copy=True)
        for i, r in enumerate(self._slots):
            if r is not None and r.prefill_pos is None:
                bad[i] = True
                break
        return bad

    def _note_poison(self):
        """Poison-storm detector (ISSUE 15): k poison events inside the
        rolling window flip speculation OFF for this engine —
        non-finite logits keep arriving, so stop spending verify-wide
        commits on them and fall back to the (pre-warmed) one-token
        decode program. The verdict survives restarts via the crash
        manifest."""
        if (not self._poison_degrade_k or not self._spec_k
                or self._degraded_spec_off):
            return
        now = time.monotonic()
        self._poison_times.append(now)
        while (self._poison_times
               and now - self._poison_times[0] > self._degraded_window_s):
            self._poison_times.popleft()
        if len(self._poison_times) >= self._poison_degrade_k:
            self._degraded_spec_off = True
            monitor.stat_add("STAT_gen_degraded_spec_off")
            self._audit.audit(
                "DEGRADED_SPEC_OFF",
                poison_events=len(self._poison_times),
                window_s=self._degraded_window_s)

    def _note_exhaust(self):
        """Admission-clamp detector (ISSUE 15): k page-blocked
        admission iterations inside the rolling window clamp admission
        — new submits the pool cannot cover RIGHT NOW fail fast with
        ResourceExhaustedError instead of queueing toward a timeout.
        Cleared by the next successful admission."""
        if not self._exhaust_clamp_k or self._admit_clamped:
            return
        now = time.monotonic()
        self._exhaust_times.append(now)
        while (self._exhaust_times
               and now - self._exhaust_times[0] > self._degraded_window_s):
            self._exhaust_times.popleft()
        if len(self._exhaust_times) >= self._exhaust_clamp_k:
            self._admit_clamped = True
            monitor.stat_add("STAT_gen_admit_clamped")
            self._audit.audit(
                "DEGRADED_ADMIT_CLAMP",
                exhaust_events=len(self._exhaust_times),
                window_s=self._degraded_window_s,
                free_pages=self._cache.free_pages)

    def _poison_decode(self, req: _GenRequest, slot: int):
        """Non-finite decode/verify logits: only THIS sequence fails,
        its pages return zeroed (shared by the plain and speculative
        step paths — one poison diagnostic shape for both)."""
        monitor.stat_add("STAT_gen_poisoned")
        self._it["poisoned"] += 1
        self._note_poison()
        self._audit.audit("POISON_DECODE", rid=req.rid, slot=slot,
                          generated=len(req.toks))
        slo.observe_request(self.name, ok=False)
        flight_recorder.dump("gen_poisoned_sequence", {
            "engine": self.name, "rid": req.rid, "stage": "decode",
            "slot": slot, "generated": len(req.toks),
            "error": "non-finite decode logits",
            "step_log_tail": (self._step_log.tail(32)
                              if self._step_log is not None else []),
            "audit_tail": self._audit.tail(64)})
        self._evict(req, FatalError(
            f"{self.name}: sequence {req.rid} produced "
            f"non-finite logits at step {len(req.toks)}"))

    def _poison_prefill(self, req: _GenRequest, bucket: int):
        """Non-finite prefill logits (whole-prompt, tail or chunk): the
        pools came back valid, so only THIS request fails and its pages
        return zeroed."""
        monitor.stat_add("STAT_gen_poisoned")
        self._it["poisoned"] += 1
        self._note_poison()
        self._audit.audit("POISON_PREFILL", rid=req.rid,
                          bucket=bucket)
        slo.observe_request(self.name, ok=False)
        flight_recorder.dump("gen_poisoned_sequence", {
            "engine": self.name, "rid": req.rid, "stage": "prefill",
            "bucket": bucket, "error": "non-finite prefill logits",
            "step_log_tail": (self._step_log.tail(32)
                              if self._step_log is not None else []),
            "audit_tail": self._audit.tail(64)})
        self._release(req)
        self._resolve_req_later(req, exc=FatalError(
            f"{self.name}: non-finite prefill logits for request "
            f"{req.rid} (poisoned prompt or weights)"))

    def _register_pages(self, req: _GenRequest, digests) -> None:
        """Index full pages in the prefix cache (matched nodes touched,
        fresh pages take a cache reference and outlive this request's
        free). With FLAGS_gen_prefix_cache_max_pages set, registration
        eagerly LRU-evicts OTHER chains back to budget — the freed
        pages are zeroed here, same hygiene as the pre-alloc
        eviction."""
        freed = self._prefix.register(digests, req.pt_row)
        if freed:
            self._zero_pages(freed)
            self._audit.audit("EVICT_PREFIX_BUDGET", rid=req.rid,
                              pages=len(freed),
                              free_pages=self._cache.free_pages)

    def _finish_prefill(self, req: _GenRequest, lg: np.ndarray,
                        digests) -> None:
        """Shared tail of every prefill flavor (whole-prompt, prefix
        tail, final chunk): register cacheable pages, sample the first
        token, mark the slot decode-live."""
        self._prefills_total += 1
        monitor.stat_add("STAT_gen_prefills")
        if self._prefix is not None and digests:
            self._register_pages(req, digests)
        tok = self._sample_host(req, lg)
        req.toks.append(tok)
        req.next_pos = int(req.prompt.size)
        self._tokens_total += 1
        monitor.stat_add("STAT_gen_tokens")
        self._it["tokens"] += 1
        self._stage_token(req, tok)
        if req.span is not None:
            req.span.stamp("prefilled")
            req.span.stamp("first_token")
            req.span.stamp("last_token")
        if self._finished(req, tok):
            self._complete(req)

    def _advance_prefills(self):
        """Advance the OLDEST partially-prefilled slot by ONE chunk
        through the per-bucket tail program (FLAGS_gen_prefill_chunk).
        One chunk per engine iteration by design: between chunks the
        loop runs a decode step for every live sequence, which is
        exactly the TPOT protection chunked prefill exists for — the
        long prompt's admission cost is spread across iterations
        instead of stalling the step thread for its whole prefill."""
        req = None
        for r in self._slots:
            if (r is not None and r.prefill_pos is not None
                    and (req is None or r.ordinal < req.ordinal)):
                req = r
        if req is None:
            return
        failpoints.maybe_raise("prefill_raise")
        S = int(req.prompt.size)
        take = min(self._cfg.prefill_chunk, S - req.prefill_pos)
        bucket = self._bucket_for(take)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :take] = req.prompt[req.prefill_pos:req.prefill_pos + take]
        t0 = _now_ms()
        with RecordEvent(f"generation::prefill_chunk[b={bucket}]"):
            with self._dev_ctx():
                out = self._prog(f"prefill_tail[b={bucket}]",
                                 self._tail_jit)(
                    self._W, *self._pools(), req.pt_row, ids,
                    np.int32(take), np.int32(req.prefill_pos))
            self._set_pools(out[:-1])
            lg = np.asarray(out[-1])
        self._it["prefill_ms"] += _now_ms() - t0
        self._it["prefill_chunks"] += 1
        self._chunks_total += 1
        monitor.stat_add("STAT_gen_prefill_chunks")
        if not np.all(np.isfinite(lg)):
            req.prefill_pos = None
            req.pending_digests = None
            self._poison_prefill(req, bucket)
            return
        req.prefill_pos += take
        if req.prefill_pos < S:
            return
        req.prefill_pos = None
        digests, req.pending_digests = req.pending_digests, None
        self._finish_prefill(req, lg, digests)

    def _sample_host(self, req: _GenRequest, logits: np.ndarray) -> int:
        """First-token sampling on host (prefill returns logits; decode
        samples in-graph). Greedy is np.argmax — first-max ties, same
        as jnp.argmax, so greedy parity with generate() holds."""
        if not req.do_sample:
            return int(np.argmax(logits))
        lg = logits / max(req.temperature, 1e-6)
        if self._cfg.top_k:
            kth = np.sort(lg)[-int(self._cfg.top_k)]
            lg = np.where(lg < kth, -1e30, lg)
        # engine-local ordinal, NOT the process-global rid: two engines
        # with the same config/seed must sample identical streams
        r = np.random.RandomState(
            (self._cfg.seed * 1000003 + req.ordinal) % (2 ** 31))
        g = -np.log(-np.log(r.uniform(1e-12, 1.0, lg.shape)))
        return int(np.argmax(lg + g))

    # -- decode step -------------------------------------------------------

    def _step_arrays(self):
        M, PP = self._cfg.max_slots, self._cfg.pages_per_seq
        toks = np.zeros((M,), np.int32)
        pos = np.zeros((M,), np.int32)
        active = np.zeros((M,), bool)
        temps = np.ones((M,), np.float32)
        smask = np.zeros((M,), bool)
        pt = np.zeros((M, PP), np.int32)
        for i, req in enumerate(self._slots):
            if req is None or req.prefill_pos is not None:
                continue  # empty, or still chunk-prefilling (no toks)
            active[i] = True
            toks[i] = req.toks[-1]
            pos[i] = req.next_pos
            temps[i] = req.temperature
            smask[i] = req.do_sample
            pt[i] = req.pt_row
        key = self._step_key()
        return pt, toks, pos, active, temps, smask, key

    def _spec_arrays(self):
        """Verify-step inputs (ISSUE 14): per-slot [current token + k
        drafts] blocks. Drafts come from the prompt-lookup proposer
        over each sequence's OWN token history, truncated to the
        request's remaining token budget (so every consumed position
        stays inside the pages the admission reserved); sampled slots
        take no drafts. Returns (args, drafted_count)."""
        M, PP = self._cfg.max_slots, self._cfg.pages_per_seq
        K = self._spec_k
        toks_blk = np.zeros((M, K + 1), np.int32)
        dmask = np.zeros((M, K), bool)
        pos = np.zeros((M,), np.int32)
        active = np.zeros((M,), bool)
        temps = np.ones((M,), np.float32)
        smask = np.zeros((M,), bool)
        pt = np.zeros((M, PP), np.int32)
        drafted = 0
        for i, req in enumerate(self._slots):
            if req is None or req.prefill_pos is not None:
                continue
            active[i] = True
            toks_blk[i, 0] = req.toks[-1]
            pos[i] = req.next_pos
            temps[i] = req.temperature
            smask[i] = req.do_sample
            pt[i] = req.pt_row
            if not req.do_sample:
                budget = min(K, req.max_new - len(req.toks) - 1)
                if budget > 0:
                    drafts = self._proposer.propose(
                        np.concatenate([req.prompt,
                                        np.asarray(req.toks, np.int32)]),
                        budget)
                    n = int(drafts.size)
                    if n:
                        toks_blk[i, 1:1 + n] = drafts
                        dmask[i, :n] = True
                        drafted += n
        key = self._step_key()
        return (pt, toks_blk, dmask, pos, active, temps, smask,
                key), drafted

    def _step_key(self):
        import jax
        if self._base_key is None:
            self._base_key = jax.random.PRNGKey(self._cfg.seed)
        return jax.random.fold_in(self._base_key, self._steps_total)

    def _step(self):
        """ONE engine step: every live sequence advances one token
        through the single compiled decode program (inactive slots are
        masked into the reserved scratch page) — or, with speculation
        on, 1 to k+1 tokens through the single compiled verify program.
        The np.asarray below is the step's only host sync."""
        if self._pre_step_hook is not None:
            self._pre_step_hook(self)
        # fault-injection seams (ISSUE 15, serving/failpoints.py): a
        # slow step first (SLO exercises), then the engine-fatal raise
        # — InjectedFault escapes to _loop exactly like a real decode
        # jit exception (the pools-donated contract)
        ms = failpoints.fire("slow_step_ms")
        if ms:
            time.sleep(ms / 1000.0)
        failpoints.maybe_raise("decode_step_raise")
        if self._spec_k and not self._degraded_spec_off:
            self._spec_step()
            return
        args = self._step_arrays()
        t0 = _now_ms()
        with RecordEvent(f"generation::step[m={self._cfg.max_slots}]"):
            out = self._decode_call(self._W, *self._pools(), *args)
            nxt = np.asarray(out[-2])
            bad = np.asarray(out[-1])
        if failpoints.fire("decode_poison_nan") is not None:
            bad = self._inject_poison(bad)
        self._set_pools(out[:-2])
        self._it["decode_ms"] += _now_ms() - t0
        self._steps_total += 1
        monitor.stat_add("STAT_gen_steps")
        for i, req in enumerate(self._slots):
            if req is None or req.prefill_pos is not None:
                continue  # empty, or chunk-prefilling (masked this step)
            if bad[i]:
                # poison isolation: only THIS sequence fails; its pages
                # are zeroed before reuse so the NaN cannot reach the
                # next owner's masked attention
                self._poison_decode(req, i)
                continue
            tok = int(nxt[i])
            req.toks.append(tok)
            req.next_pos += 1
            self._tokens_total += 1
            monitor.stat_add("STAT_gen_tokens")
            self._it["tokens"] += 1
            self._stage_token(req, tok)
            if req.span is not None:
                req.span.stamp("last_token")
            if self._finished(req, tok):
                self._complete(req)

    def _spec_step(self):
        """ONE speculative engine step (ISSUE 14): every live sequence
        advances 1 to k+1 tokens through the single compiled verify
        program — the current token plus the longest prefix of its
        prompt-lookup drafts the model greedily agrees with, plus the
        bonus token the verify pass scored at the first disagreement.
        Rejected draft positions were scratch-routed in-graph, so there
        is nothing to undo on the host; acceptance is exact greedy
        agreement, so the token stream is identical to the one the
        plain decode program would have produced, just delivered in
        fewer weight streams."""
        args, drafted = self._spec_arrays()
        t0 = _now_ms()
        with RecordEvent(f"generation::verify[k={self._spec_k}]"):
            out = self._verify_call(self._W, *self._pools(), *args)
            n_acc = np.asarray(out[-3])
            nxt = np.asarray(out[-2])
            bad = np.asarray(out[-1])
        if failpoints.fire("decode_poison_nan") is not None:
            bad = self._inject_poison(bad)
        self._set_pools(out[:-3])
        self._it["decode_ms"] += _now_ms() - t0
        self._steps_total += 1
        monitor.stat_add("STAT_gen_steps")
        if drafted:
            monitor.stat_add("STAT_spec_drafted", drafted)
            self._it["spec_drafted"] += drafted
            self._spec_drafted_total += drafted
        toks_blk = args[1]
        for i, req in enumerate(self._slots):
            if req is None or req.prefill_pos is not None:
                continue
            if bad[i]:
                self._poison_decode(req, i)
                continue
            acc = int(n_acc[i])
            if acc:
                monitor.stat_add("STAT_spec_accepted", acc)
                self._it["spec_accepted"] += acc
                self._spec_accepted_total += acc
                req.spec_accepted += acc
            # accepted drafts in order, then the bonus token; EOS (or
            # the max-new budget) inside the block ends the sequence
            # there — later committed positions sit past next_pos,
            # masked from every future attend and zeroed with the free
            for tok in ([int(t) for t in toks_blk[i, 1:1 + acc]]
                        + [int(nxt[i])]):
                req.toks.append(tok)
                req.next_pos += 1
                self._tokens_total += 1
                monitor.stat_add("STAT_gen_tokens")
                self._it["tokens"] += 1
                self._stage_token(req, tok)
                if self._finished(req, tok):
                    break
            if req.span is not None:
                req.span.stamp("last_token")
            if self._finished(req, req.toks[-1]):
                self._complete(req)

    def _finished(self, req: _GenRequest, tok: int) -> bool:
        return ((req.eos is not None and tok == req.eos)
                or len(req.toks) >= req.max_new)

    def _expire_active(self):
        """Per-step deadline enforcement: an expired non-streaming
        sequence cancels mid-decode — pages freed the same step, only
        its future fails. A STREAMING sequence's whole-request deadline
        is soft once tokens flow (ISSUE 12): expiry stops decoding the
        same step but resolves with the tokens already delivered —
        they left the engine and cannot be retracted — still counted as
        a timeout (STAT_gen_timeouts, SLO error)."""
        t = _now_ms()
        for req in list(self._slots):
            if req is None:
                continue
            deadlines = [req.deadline_ms] if req.deadline_ms else []
            if req.ttft_deadline_ms is not None and not req.toks:
                # a chunk-prefilling stream has been admitted but has
                # no first token yet: its HARD TTFT deadline still
                # applies (pre-chunking, admission implied an immediate
                # prefill so this window could never be observed live)
                deadlines.append(req.ttft_deadline_ms)
            if not deadlines:
                continue
            if t > min(deadlines):
                monitor.stat_add("STAT_gen_timeouts")
                self._it["expired"] += 1
                self._audit.audit(
                    "EXPIRE_DECODE", rid=req.rid, slot=req.slot,
                    generated=len(req.toks),
                    stream=req.stream is not None,
                    age_ms=round(t - req.t_enqueue_ms, 3))
                slo.observe_request(self.name, ok=False)
                if (req.stream is not None and req.toks
                        and req.skip_stream == 0):
                    # soft: pages freed now, stream closed normally,
                    # future resolves with the partial sequence.
                    # skip_stream > 0 (a from-scratch replay still
                    # re-deriving tokens an earlier incarnation
                    # delivered) takes the HARD path below instead —
                    # resolving now would hand back FEWER generated
                    # tokens than the caller already streamed
                    self._release(req)
                    self._resolve_req_later(req, result=np.concatenate(
                        [req.prompt, np.asarray(req.toks, np.int32)]))
                    if req.span is not None:
                        req.span.stamp("resolved")
                        req.span.finish(len(req.toks),
                                        prefix_tokens=req.prefix_tokens,
                                        spec_tokens=req.spec_accepted)
                    continue
                ttft_hit = (req.ttft_deadline_ms is not None
                            and not req.toks
                            and t > req.ttft_deadline_ms)
                self._evict(req, ExecutionTimeoutError(
                    f"{self.name}: request {req.rid} missed its HARD "
                    f"TTFT deadline after {t - req.t_enqueue_ms:.1f}ms "
                    f"admitted but still prefilling (no first token)"
                    if ttft_hit else
                    f"{self.name}: request {req.rid} expired after "
                    f"{t - req.t_enqueue_ms:.1f}ms with "
                    f"{len(req.toks)}/{req.max_new} tokens decoded "
                    f"(whole-request deadlines are hard for "
                    f"non-streaming submits; no partial result is "
                    f"delivered)"))

    # -- completion / eviction ---------------------------------------------

    def _release(self, req: _GenRequest):
        """Return the request's slot + pages (pages zeroed on device)."""
        pages = self._cache.free(req.rid)
        if pages:
            self._zero_pages(pages)
            self._exhaust_dumped = False  # pages freed: new episode
        if req.slot is not None and self._slots[req.slot] is req:
            self._slots[req.slot] = None
            self._it["freed"] += 1
        with self._cv:
            self._cv.notify_all()

    def _complete(self, req: _GenRequest):
        out = np.concatenate([req.prompt,
                              np.asarray(req.toks, np.int32)])
        if self._prefix is not None and req.pt_row is not None:
            # generated-suffix registration (ISSUE 14): index the full
            # pages of prompt + answer BEFORE the release, so a
            # follow-up turn whose prompt is this whole conversation
            # (prompt_n+1 = prompt_n + answer_n, the agent-loop shape)
            # walks the chain end-to-end. Only pages fully covered by
            # WRITTEN positions qualify: the final token's K/V is never
            # written (it was sampled, not stepped), so the chain stops
            # at next_pos — registering past it would serve zeros
            self._register_pages(
                req, self._prefix.digests(out)[:req.next_pos
                                               // self._cfg.page_size])
        self._release(req)
        t_done = _now_ms()
        self._hist.observe(t_done - req.t_enqueue_ms)
        if req.deadline_ms is not None and t_done > req.deadline_ms:
            # finished the same instant it expired: honor the deadline
            # (a timeout, NOT a completion — the two counters partition
            # the finished-naturally outcomes)
            monitor.stat_add("STAT_gen_timeouts")
            self._it["expired"] += 1
            self._audit.audit("EXPIRE_LATE", rid=req.rid,
                              generated=len(req.toks))
            slo.observe_request(self.name, ok=False)
            if req.stream is not None:
                # the stream's whole-request deadline is soft: tokens
                # already left, deliver the (complete) sequence
                self._resolve_req_later(req, result=out)
                if req.span is not None:
                    req.span.stamp("resolved")
                    req.span.finish(len(req.toks),
                                    prefix_tokens=req.prefix_tokens,
                                    spec_tokens=req.spec_accepted)
                return
            self._resolve_later(req, req.future, exc=ExecutionTimeoutError(
                f"{self.name}: request expired after "
                f"{t_done - req.t_enqueue_ms:.1f}ms"))
            return
        # delivery cannot fail: _admit claimed the future via
        # set_running_or_notify_cancel, so a caller-side cancel is no
        # longer possible — count now, resolve after the ring record
        # (the stream's end marker flushes before the future resolves)
        self._resolve_req_later(req, result=out)
        monitor.stat_add("STAT_gen_completions")  # delivered results
        self._it["completed"] += 1
        self._audit.audit(
            "COMPLETE_EOS" if (req.eos is not None
                               and req.toks
                               and req.toks[-1] == req.eos)
            else "COMPLETE_MAX_NEW",
            rid=req.rid, generated=len(req.toks),
            e2e_ms=round(t_done - req.t_enqueue_ms, 3))
        slo.observe_request(self.name, ok=True)
        if req.span is not None:
            req.span.stamp("resolved")
            req.span.finish(len(req.toks),
                            prefix_tokens=req.prefix_tokens,
                            spec_tokens=req.spec_accepted)

    def _evict(self, req: _GenRequest, err: BaseException):
        """Cancel a LIVE sequence mid-decode: free + zero its pages,
        fail only its own future (and stream, when present)."""
        self._release(req)
        monitor.stat_add("STAT_gen_evictions")
        self._resolve_req_later(req, exc=err)

    def _evict_all(self, err: BaseException):
        for req in list(self._slots):
            if req is not None:
                # deliberate operator action (shutdown/abort): audited
                # but NOT an SLO error — a drain must not burn the
                # error budget of the replicas still serving
                self._it["aborted"] += 1
                self._audit.audit("EVICT_SHUTDOWN", rid=req.rid,
                                  generated=len(req.toks))
                self._evict(req, err)

    # -- lifecycle / introspection -----------------------------------------

    def stats(self) -> dict:
        """Engine snapshot: per-slot state, page-pool occupancy + KV
        introspection, the exact compile ledger, token/step totals, and
        the TTFT/TPOT + end-to-end latency histograms."""
        with self._cv:
            depth = len(self._queue)
            slots = [{"slot": i,
                      "rid": r.rid if r is not None else None,
                      "generated": len(r.toks) if r is not None else 0,
                      "prompt_len": int(r.prompt.size)
                      if r is not None else 0}
                     for i, r in enumerate(self._slots)]
            decode_tokens = self._tokens_total - self._prefills_total
            slot_of = {r.rid: i for i, r in enumerate(self._slots)
                       if r is not None}
            ledger = dict(self._ledger)
            loaded = dict(self._loaded)
            steps, prefills, tokens = (self._steps_total,
                                       self._prefills_total,
                                       self._tokens_total)
        # warm start (ISSUE 16): per-program provenance — a store-
        # covered program that deserialized reports "loaded" (its
        # ledger entry never moved), a traced one reports "compiled";
        # the acceptance criterion reads this mapping directly
        programs = {name: ("loaded" if loaded.get(name)
                           and not ledger.get(name) else "compiled")
                    for name in set(ledger) | set(loaded)}
        return {
            "slots": slots,
            "queue_depth": depth,
            "pages": self._cache.stats(),
            "kv": self._kv_introspection(slot_of),
            "compiles": ledger,
            "loaded": loaded,
            "programs": programs,
            "program_store": {
                "configured": bool(self._cfg.program_store),
                "active": self._store is not None,
                "key": self._store.key if self._store is not None
                else None,
                "dir": self._store.key_dir if self._store is not None
                else None,
            },
            "steps": steps,
            "prefills": prefills,
            "tokens": tokens,
            # mesh-slice lane (ISSUE 19): slice degree + what one chip
            # of it holds (pages stats carry the per-shard bytes too)
            "tp": self._tp,
            # speculative decoding + chunked prefill (ISSUE 14): the
            # acceptance economics (tokens_per_step > 1 is the win) and
            # the chunk count the bench + reports read
            "spec": {
                "enabled": bool(self._spec_k),
                "k": self._spec_k,
                "drafted": self._spec_drafted_total,
                "accepted": self._spec_accepted_total,
                "acceptance_rate": round(
                    self._spec_accepted_total
                    / max(1, self._spec_drafted_total), 4),
                # decode-delivered tokens per decode step — every
                # successful prefill delivers exactly one token, so
                # subtracting prefills leaves the honest speculation
                # signal (> 1.0 only when drafts were accepted)
                "tokens_per_step": round(
                    decode_tokens / max(1, steps), 4),
            },
            "prefill_chunks": self._chunks_total,
            # fault tolerance (ISSUE 15): which engine generation this
            # is, and whether a degraded mode is active
            "incarnation": self.incarnation,
            "degraded": {
                "spec_off": self._degraded_spec_off,
                "admit_clamped": self._admit_clamped,
                "poison_degrade_k": self._poison_degrade_k,
                "exhaust_clamp_k": self._exhaust_clamp_k,
            },
            "step_log": {
                "enabled": self._step_log is not None,
                "recorded": (self._step_log.recorded
                             if self._step_log is not None else 0),
                "audit_events": self._audit.recorded,
            },
            "latency_ms": self._hist.snapshot(),
            "ttft_ms": monitor.histogram("ttft_ms").snapshot(),
            "tpot_ms": monitor.histogram("tpot_ms").snapshot(),
        }

    def _kv_introspection(self, slot_of=None) -> dict:
        """`stats()["kv"]`: pool stats + watermarks, the per-sequence
        page-ownership map (joined to decode slots), and the admission-
        headroom estimate for this engine's representative request
        shapes — one `can_admit` count per (prefill bucket + default
        max-new) total, the per-replica pressure surface the router
        tier compares (ISSUE 11)."""
        out = dict(self._cache.stats())
        owners = self._cache.owners()
        if slot_of is None:
            with self._cv:
                slot_of = {r.rid: i for i, r in enumerate(self._slots)
                           if r is not None}
        out["owners"] = [
            {"rid": rid, "slot": slot_of.get(rid), "pages": pages}
            for rid, pages in sorted(owners.items())]
        # prefix-cache surface (ISSUE 12): hit/eviction counters + the
        # cached/evictable page split the admission arithmetic uses
        out["prefix"] = (self._prefix.stats() if self._prefix is not None
                         else {"enabled": False})
        shapes = {b + self._cfg.max_new_tokens
                  for b in self._cfg.prefill_buckets}
        out["admit_headroom"] = {
            str(tokens): n
            for tokens, n in sorted(
                self._cache.headroom(sorted(shapes)).items())}
        return out

    def _compute_pressure(self) -> dict:
        """Step-thread half of `pressure()`: admission headroom per
        representative request shape (prefill bucket + default max-new,
        the same shapes as stats()["kv"]["admit_headroom"]), pool
        occupancy, and slot availability. Called only from __init__
        (before the step thread exists) and `_record_iteration` (on it),
        and published as one plain-dict attribute store — the atomic
        handoff `pressure()` reads."""
        shapes = sorted({b + self._cfg.max_new_tokens
                         for b in self._cfg.prefill_buckets})
        snap = {
            "headroom": {str(t): n for t, n in sorted(
                self._cache.headroom(shapes).items())},
            "free_pages": self._cache.free_pages,
            "pages_in_use": self._cache.pages_in_use,
            "slots_free": sum(1 for r in self._slots if r is None),
            "live": self._num_active(),
            # mesh-slice lane (ISSUE 19): page counts above are
            # tp-invariant (the page axis is FULL on every shard);
            # kv_shard_bytes is what ONE chip of the slice pays — the
            # per-device HBM reality the router compares (== the whole
            # pool for a single-chip lane)
            "tp": self._tp,
            "kv_shard_bytes": self._cache.shard_hbm_bytes(),
        }
        if self._tier is not None:
            # host-tier surface (ISSUE 18): the router folds the tier
            # hit-rate into placement the same way the headroom fields
            # feed least-pressure — a replica resurrecting prefixes
            # from host RAM is cheaper than one prefilling them cold
            snap["tier"] = {
                "host_bytes": self._tier.host_bytes,
                "entries": len(self._tier),
                "hit_rate": round(
                    self._tier.hits
                    / max(1, self._prefix.hits + self._prefix.misses),
                    4),
            }
        return snap

    def pressure(self) -> dict:
        """Cheap per-replica pressure snapshot for the router tier
        (ISSUE 17): page/slot fields come from the step thread's last
        published `_compute_pressure()` dict (read as one GIL-atomic
        attribute load — NO engine lock taken, so a polling router can
        never contend the step loop), while queue depth and oldest-queue
        age are overlaid live — the queue grows on the submitter side
        between iterations and staleness there is exactly what a
        balancer must see. `len(deque)` and `deque[0]` are GIL-atomic;
        the head may race an admit's popleft, hence the IndexError arm."""
        snap = dict(self._pressure)
        q = self._queue
        snap["queue_depth"] = len(q)
        try:
            snap["oldest_age_ms"] = round(
                _now_ms() - q[0].t_enqueue_ms, 3)
        except IndexError:
            snap["oldest_age_ms"] = 0.0
        snap["queue_limit"] = self._cfg.max_queue_depth
        return snap

    def health(self) -> dict:
        """`/readyz` verdict, same shape as InferenceEngine.health() so
        the router tier drains generation replicas identically."""
        with self._cv:
            depth = len(self._queue)
            draining = self._closed
            live = int(getattr(self, "_thread", None) is not None
                       and self._thread.is_alive() and self._death is None)
            slots_free = sum(1 for r in self._slots if r is None)
        limit = self._cfg.max_queue_depth
        warmed = self._warmed
        if draining:
            reason = "draining"
        elif not warmed:
            reason = "warming up"
        elif not live:
            reason = "step loop dead"
        elif depth >= limit:
            reason = "queue at rejection threshold"
        else:
            # SLO folding (ISSUE 11): with FLAGS_slo_max_burn_rate set,
            # a replica burning its error budget too fast reports
            # not-ready so the router sheds load BEFORE the budget is
            # gone — the pre-emptive drain surface
            reason = slo.shed_verdict(self.name) or "ok"
        return {"ready": reason == "ok", "reason": reason,
                "warmup_complete": warmed, "draining": draining,
                "live_lanes": live, "queue_depth": depth,
                "queue_limit": limit, "slots_free": slots_free,
                "slots": self._cfg.max_slots}

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None):
        """Stop intake; by default every queued + live sequence finishes
        before the step loop exits. drain=False fails pending futures
        fast (live sequences are evicted, pages freed)."""
        dropped = []
        with self._cv:
            self._closed = True
            if not drain:
                self._abort = True
                while self._queue:
                    req = self._queue.popleft()
                    monitor.stat_sub("STAT_gen_queue_depth")
                    dropped.append(req)
                    err = UnavailableError(
                        f"{self.name}: engine shut down")
                    if req.stream is not None:
                        req.stream._put(err)  # never admitted: no
                        # barrier to honor, nothing was recorded
                    try:
                        req.future.set_exception(err)
                    except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
                        pass
            self._cv.notify_all()
        for req in dropped:
            # audited OUTSIDE the lock (disk sink); queued drops get
            # their own code so the step ring's aborted count still
            # reconciles exactly with the live EVICT_SHUTDOWN events
            self._audit.audit("EVICT_SHUTDOWN_QUEUED", rid=req.rid,
                              queued_ms=round(_now_ms()
                                              - req.t_enqueue_ms, 3))
        t = getattr(self, "_thread", None)
        if t is not None:
            t.join(timeout_s)
        exporter.unregister_engine(self)
        if self._step_log is not None:
            step_log.unregister(self._step_log)
        self._audit.close()
        slo.forget(self.name)
        if getattr(self, "_owns_metrics_server", False) \
                and self.metrics_server is not None:
            self.metrics_server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
