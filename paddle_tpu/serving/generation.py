"""Continuous-batching generation engine over a paged KV cache.

The PR 2/3 engine is one-shot: a request enters a bucket, runs once,
leaves. Autoregressive decode — the dominant production inference
workload — needs **iteration-level scheduling** (Orca) over a
**paged KV cache** (vLLM): requests join the running batch via a
prefill pass, every engine step advances EVERY live sequence by one
token through a single jitted decode program, and sequences leave on
EOS / max-tokens / deadline, freeing their pages the same step.

Shape discipline is what makes this TPU-native: the decode batch is a
FIXED number of slots (`FLAGS_gen_max_slots`) with inactive slots
masked, and prompts pad up to `FLAGS_gen_prefill_buckets`, so XLA
compiles exactly **one decode step** and **one prefill per bucket** —
sequences joining and leaving mid-decode never retrace (the compile
ledger in `stats()` proves it, the same exactness contract as the PR 3
per-(device, bucket) ledgers). K/V lives in `serving.PagedKVCache`
pools; on TPU the Pallas `paged_attention` kernel reads pages in place,
elsewhere a dense gather reference keeps the math bit-anchored to
`GPTModel.generate` (`ops/paged_ops.py`). With
`kv_cache_dtype="int8"` (FLAGS_kv_cache_dtype) the pools store int8
pages + per-(layer, head, page) scale pools — quantize-on-append,
dequantize-on-read, ~4x the concurrent sequences per HBM byte; parity
vs fp32 pages is token-level (different compiled programs). A
`quantize_weights`'d model composes independently: its decode-weight
pytree carries (int8, scale) leaves dequantized in-graph.

Hardening carries over from the one-shot engine, re-expressed at token
granularity: bounded intake (`EngineOverloaded`), worst-case page
admission control (a request is only admitted when the allocator can
cover prompt + max-new, so running sequences are never starved;
exhaustion defers admission and dumps a flight record), per-request
deadlines enforced before EVERY decode step (a mid-decode expiry
cancels just that sequence and frees its pages), poison isolation via
per-slot non-finite-logit flags (a poisoned sequence fails only its own
future; its pages are zeroed before reuse so NaNs cannot leak through
masked attention into the next owner), shutdown-drain, and
`/readyz`-compatible `health()`. TTFT/TPOT spans feed the `ttft_ms` /
`tpot_ms` histograms and `reqspan:` trace instants
(`tools/latency_report.py`).

Single-device by design: one engine owns one chip's pools and step
loop (the PR 3 lane made token-level — collector and lane collapse into
one step thread because the decode batch IS the lane). Data-parallel
scale-out = one engine per chip behind the router tier's `/readyz`.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from ..framework import monitor
from ..framework.errors import (ExecutionTimeoutError, FatalError,
                                InvalidArgumentError,
                                ResourceExhaustedError, UnavailableError)
from ..framework.flags import flag
from ..profiler import (RecordEvent, audit, device_telemetry, exporter,
                        flight_recorder, slo, spans, step_log)
from .kv_cache import TRASH_PAGE, PagedKVCache

# the intake queue legitimately moves both ways; registering it as an
# "updown" gauge makes the exporter render a Prometheus gauge while the
# cross-process relay keeps summing its stat_add/stat_sub deltas
# (monitor is the single registry of gauge names — ISSUE 11)
monitor.register_gauge("STAT_gen_queue_depth", updown=True)

__all__ = ["GenerationConfig", "GenerationEngine"]


def _now_ms() -> float:
    return time.perf_counter() * 1000.0


class GenerationConfig:
    """Continuous-batching knobs; defaults ride the FLAGS_gen_* /
    FLAGS_paged_* registry so deployments tune engines without code
    changes."""

    def __init__(self, max_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 pages_per_seq: Optional[int] = None,
                 prefill_buckets=None,
                 max_new_tokens: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 request_timeout_ms: Optional[float] = None,
                 kv_cache_dtype: Optional[str] = None,
                 top_k: int = 0, seed: int = 0, warmup: bool = True):
        self.max_slots = int(flag("FLAGS_gen_max_slots")
                             if max_slots is None else max_slots)
        if self.max_slots < 1:
            raise InvalidArgumentError("max_slots must be >= 1")
        self.page_size = int(flag("FLAGS_paged_page_size")
                             if page_size is None else page_size)
        self.num_pages = int(flag("FLAGS_paged_num_pages")
                             if num_pages is None else num_pages)
        self.pages_per_seq = int(flag("FLAGS_paged_pages_per_seq")
                                 if pages_per_seq is None else pages_per_seq)
        if prefill_buckets is None:
            raw = str(flag("FLAGS_gen_prefill_buckets"))
            prefill_buckets = [int(x) for x in raw.split(",") if x.strip()]
        buckets = sorted({int(b) for b in prefill_buckets if int(b) >= 1})
        if not buckets:
            raise InvalidArgumentError("prefill_buckets must be non-empty")
        self.prefill_buckets = tuple(buckets)
        self.max_new_tokens = int(flag("FLAGS_gen_max_new_tokens")
                                  if max_new_tokens is None
                                  else max_new_tokens)
        self.max_queue_depth = int(flag("FLAGS_gen_max_queue_depth")
                                   if max_queue_depth is None
                                   else max_queue_depth)
        self.request_timeout_ms = float(
            flag("FLAGS_gen_request_timeout_ms")
            if request_timeout_ms is None else request_timeout_ms)
        self.kv_cache_dtype = str(flag("FLAGS_kv_cache_dtype")
                                  if kv_cache_dtype is None
                                  else kv_cache_dtype)
        if self.kv_cache_dtype not in ("auto", "int8", "float32",
                                       "bfloat16"):
            raise InvalidArgumentError(
                f"kv_cache_dtype must be auto/int8/float32/bfloat16, "
                f"got {self.kv_cache_dtype!r}")
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.warmup = bool(warmup)


class _GenRequest:
    __slots__ = ("rid", "prompt", "max_new", "eos", "do_sample",
                 "temperature", "future", "deadline_ms", "t_enqueue_ms",
                 "span", "slot", "pt_row", "toks", "next_pos", "ordinal",
                 "defer_logged")

    _ids = itertools.count(1)

    def __init__(self, prompt, max_new, eos, do_sample, temperature,
                 future, deadline_ms, t_enqueue_ms, span):
        self.rid = next(self._ids)
        self.prompt = prompt            # np.int32 [S]
        self.max_new = max_new
        self.eos = eos
        self.do_sample = do_sample
        self.temperature = temperature
        self.future = future
        self.deadline_ms = deadline_ms
        self.t_enqueue_ms = t_enqueue_ms
        self.span = span                # GenSpan or None
        self.slot: Optional[int] = None
        self.pt_row = None              # np.int32 [pages_per_seq]
        self.toks: List[int] = []       # generated tokens (eos included)
        self.next_pos = 0               # cache position the NEXT step writes
        self.ordinal = 0                # engine-local submit ordinal
        self.defer_logged = set()       # audit DEFER_* causes noted once


class GenerationEngine:
    """Token-level continuous-batching front-end over a
    `models.GPTForCausalLM`.

    `submit(prompt_ids, ...)` returns a `concurrent.futures.Future`
    resolving to the full token sequence (prompt + generated, numpy
    int32). Greedy by default; `do_sample=True` draws from the
    temperature-scaled distribution using the ENGINE's PRNG stream
    (`config.seed` folded with the step counter — per-request seeds
    don't exist because co-resident sequences share each step's
    program).

    Scheduling contract: admission is FIFO with head-of-line blocking —
    a request is admitted the moment a slot AND its worst-case pages
    (prompt + max_new) are both available, prefills immediately, and
    joins the very next decode step. Deadlines are whole-request and
    checked before every step; an expired sequence is cancelled
    mid-decode with nothing delivered (deadline semantics are
    streaming-unsafe by design — there is no partial result).

    Numerics: decode always runs the one compiled [max_slots] program,
    so a sequence's tokens are independent of WHO shares the batch
    (row-independent math) and bit-stable across repeats on one engine
    config. Comparisons against `GPTModel.generate` cross program/shape
    boundaries and hold at token level (greedy) / float tolerance, per
    the standard XLA per-shape caveat.
    """

    def __init__(self, model, config: Optional[GenerationConfig] = None,
                 name: str = "generation", device=None,
                 metrics_port: Optional[int] = None, **overrides):
        if config is None:
            config = GenerationConfig(**overrides)
        elif overrides:
            raise InvalidArgumentError(
                "pass either a GenerationConfig or keyword overrides, "
                "not both")
        import copy
        self._cfg = copy.copy(config)
        self.name = name
        from ..models.gpt import GPTForCausalLM
        if not isinstance(model, GPTForCausalLM):
            raise InvalidArgumentError(
                f"GenerationEngine serves a models.GPTForCausalLM "
                f"(got {type(model).__name__})")
        self._model = model
        mcfg = model.gpt.config
        self._W = model.decode_weights()  # raises for MoE
        self._H = mcfg.num_heads
        self._D = mcfg.hidden_size // mcfg.num_heads
        self._scale = 1.0 / self._D ** 0.5
        self._max_position = mcfg.max_position_embeddings
        if self._cfg.pages_per_seq <= 0:
            self._cfg.pages_per_seq = -(-self._max_position
                                        // self._cfg.page_size)
        # buckets are bounded by the PER-SEQUENCE page capacity too, not
        # just max_position: a wider bucket would compute page indices
        # past the table width, which the gather CLAMPS onto the
        # sequence's last real page — pad-token K/V would silently
        # overwrite prompt state there
        cap = min(self._max_position,
                  self._cfg.pages_per_seq * self._cfg.page_size)
        self._cfg.prefill_buckets = tuple(sorted(
            {min(int(b), cap) for b in self._cfg.prefill_buckets}))
        self._device = device
        dtype = np.asarray(self._W["lnf"][0]).dtype
        kv_dtype = (str(dtype) if self._cfg.kv_cache_dtype == "auto"
                    else self._cfg.kv_cache_dtype)
        self._cache = PagedKVCache(
            mcfg.num_layers, self._H, self._D, self._cfg.page_size,
            self._cfg.num_pages, self._cfg.pages_per_seq, dtype=kv_dtype)
        # int8 page mode: quantize-on-append decode/prefill programs
        # thread the parallel scale pools (donated alongside the pages);
        # everything above this line — admission arithmetic, page
        # tables, zero-on-free, the compile ledger — is dtype-blind
        self._quant_kv = self._cache.quantized
        self._kp = self._cache.k_pages
        self._vp = self._cache.v_pages
        self._ks = self._cache.k_scales
        self._vs = self._cache.v_scales

        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._slots: List[Optional[_GenRequest]] = \
            [None] * self._cfg.max_slots
        self._closed = False
        self._abort = False
        # futures whose resolution is held until this iteration's
        # step-ring record lands (step-thread only; see _resolve_later)
        self._resolve_q: List[tuple] = []
        self._warmed = False
        self._steps_total = 0
        self._prefills_total = 0
        self._tokens_total = 0
        self._exhaust_dumped = False   # one flight dump per episode
        self._req_seq = 0              # engine-local submit ordinal
        self._ledger = {}              # "decode[m=M]"/"prefill[b=S]" -> traces
        self._death: Optional[BaseException] = None
        self._pre_step_hook = None     # test seam: runs on the step thread
        self._hist = monitor.histogram(f"{name}_request_ms")
        self._base_key = None          # PRNGKey, built lazily on first use
        # scheduler X-ray (ISSUE 11): decision audit ring (always on —
        # one deque append per decision) + per-iteration step ring
        # (FLAGS_gen_step_log; snapshot at construction so one engine's
        # A/B arm can't half-enable the other's)
        self._audit = audit.AuditLog(name)
        self._step_log = (step_log.StepLog(name)
                          if step_log.enabled() else None)
        self._iters = 0
        self._it = {"admitted": 0, "completed": 0, "expired": 0,
                    "poisoned": 0, "aborted": 0, "freed": 0,
                    "prefill_ms": 0.0, "decode_ms": 0.0}

        self._build_programs()
        flight_recorder.touch()
        device_telemetry.touch()
        exporter.register_engine(self)
        try:
            if self._cfg.warmup:
                self._warmup()
            self._warmed = True
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=f"{name}-genstep")
            self._thread.start()
            self._owns_metrics_server = (metrics_port is not None
                                         and int(metrics_port) == 0)
            self.metrics_server = None
            self.metrics_server = exporter.start_metrics_server(
                metrics_port)
        except Exception:
            exporter.unregister_engine(self)
            if self._step_log is not None:
                step_log.unregister(self._step_log)
            raise

    # -- jitted programs ---------------------------------------------------

    def _note_trace(self, key: str):
        # runs at TRACE time only (python side effect under jit), so the
        # ledger counts compiles exactly — the same accounting trick as
        # Predictor.compile_count
        self._ledger[key] = self._ledger.get(key, 0) + 1
        monitor.stat_add("STAT_gen_compiles")

    def _pools(self):
        """The donated device-pool tuple the jitted programs thread:
        (k_pages, v_pages) — plus the parallel scale pools in the int8
        page mode."""
        if self._quant_kv:
            return (self._kp, self._vp, self._ks, self._vs)
        return (self._kp, self._vp)

    def _set_pools(self, pools):
        if self._quant_kv:
            self._kp, self._vp, self._ks, self._vs = pools
        else:
            self._kp, self._vp = pools

    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ..models.gpt import gpt_decode_step, gpt_logits, gpt_prefill
        from ..ops.paged_ops import (page_rows_for_positions,
                                     paged_attention, paged_write,
                                     paged_write_quantized)

        H, P, scale = self._H, self._cfg.page_size, self._scale
        top_k = self._cfg.top_k
        quant = self._quant_kv
        # pools per program signature: (kp, vp) or (kp, vp, ks, vs) —
        # the int8 mode's scale pools ride (and are donated) alongside
        # the pages so quantize-on-append updates both in place
        NP = self._npool = 4 if quant else 2
        eng = self

        def write_pages(pools, layer, page_ids, offs, k, v):
            if quant:
                kp, vp, ksc, vsc = pools
                kp, ksc = paged_write_quantized(kp, ksc, layer, page_ids,
                                                offs, k)
                vp, vsc = paged_write_quantized(vp, vsc, layer, page_ids,
                                                offs, v)
                return (kp, vp, ksc, vsc)
            kp, vp = pools
            # a forced narrower page dtype (kv_cache_dtype="bfloat16"
            # under an fp32 model) is a deliberate storage downcast
            return (paged_write(kp, layer, page_ids, offs,
                                k.astype(kp.dtype)),
                    paged_write(vp, layer, page_ids, offs,
                                v.astype(vp.dtype)))

        def prefill_fn(W, *rest):
            pools, (pt_row, ids, length) = rest[:NP], rest[NP:]
            eng._note_trace(f"prefill[b={ids.shape[1]}]")
            h, ks, vs = gpt_prefill(W, ids, num_heads=H, scale=scale)
            S_b = ids.shape[1]
            pos = jnp.arange(S_b)
            page_ids, offs = page_rows_for_positions(pt_row, pos, P)
            # bucket-pad tail positions (pos >= length) write to the
            # reserved scratch page, never the sequence's own pages —
            # the documented contract, and load-bearing in the int8
            # mode: the scatter-max page scales must not bake pad-token
            # K/V magnitudes into a real page's quantization grid (the
            # grid only ever widens, so the pollution would be
            # permanent; fp32 merely overwrites the junk later)
            valid = pos < length
            page_ids = jnp.where(valid, page_ids, TRASH_PAGE)
            offs = jnp.where(valid, offs, 0)
            pools = write_pages(pools, None, page_ids, offs,
                                ks[:, 0], vs[:, 0])
            idx = jnp.clip(length - 1, 0, S_b - 1)
            return (*pools, gpt_logits(W, h[0, idx]))

        def write_kv(cache, layer, k, v, pos):
            pools, pt = cache
            page_ids, offs = page_rows_for_positions(pt, pos, P)
            return (write_pages(pools, layer, page_ids, offs, k, v), pt)

        def attend(cache, layer, q, pos):
            pools, pt = cache
            if quant:
                kp, vp, ksc, vsc = pools
                return paged_attention(q, kp[layer], vp[layer], pt, pos,
                                       scale, ksc[layer], vsc[layer])
            kp, vp = pools
            return paged_attention(q, kp[layer], vp[layer], pt, pos, scale)

        def decode_fn(W, *rest):
            pools = rest[:NP]
            pt, tok, pos, active, temps, smask, key = rest[NP:]
            eng._note_trace(f"decode[m={tok.shape[0]}]")
            logits, (pools, _) = gpt_decode_step(
                W, tok, pos, (pools, pt), write_kv, attend,
                num_heads=H, scale=scale)
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            lg = logits / jnp.maximum(temps[:, None], 1e-6)
            if top_k:
                kth = jax.lax.top_k(lg, int(top_k))[0][..., -1:]
                lg = jnp.where(lg < kth, -1e30, lg)
            sampled = jax.random.categorical(key, lg).astype(jnp.int32)
            nxt = jnp.where(smask, sampled, greedy)
            bad = active & ~jnp.all(jnp.isfinite(logits), axis=-1)
            return (*pools, jnp.where(active, nxt, 0), bad)

        def zero_fn(*rest):
            # trash-padded page rows: the scratch page is re-zeroed with
            # every free, which also scrubs poisoned prefill tails; the
            # int8 mode resets the freed pages' SCALES too, so the next
            # owner starts from a clean quantization grid and a poisoned
            # page's scale can't survive its content
            pools, pages = rest[:NP], rest[NP]
            if quant:
                kp, vp, ksc, vsc = pools
                return (kp.at[:, :, pages].set(0),
                        vp.at[:, :, pages].set(0),
                        ksc.at[:, :, pages].set(0.0),
                        vsc.at[:, :, pages].set(0.0))
            kp, vp = pools
            return (kp.at[:, :, pages].set(0.0),
                    vp.at[:, :, pages].set(0.0))

        donate = tuple(range(1, 1 + NP))
        self._prefill_jit = jax.jit(prefill_fn, donate_argnums=donate)
        self._decode_jit = jax.jit(decode_fn, donate_argnums=donate)
        self._zero_jit = jax.jit(zero_fn,
                                 donate_argnums=tuple(range(NP)))

    def _dev_ctx(self):
        import jax
        import contextlib
        return (jax.default_device(self._device)
                if self._device is not None else contextlib.nullcontext())

    def _decode_call(self, *args):
        """One jitted decode dispatch (seam: tests wrap this to inject
        per-slot failures)."""
        with self._dev_ctx():
            return self._decode_jit(*args)

    def _zero_pages(self, pages):
        row = self._cache.zero_rows(pages)
        with self._dev_ctx():
            self._set_pools(self._zero_jit(*self._pools(), row))

    def _warmup(self):
        """Compile every prefill bucket + the decode step + the zeroing
        scatter up front: no live request pays a compile, and the
        ledger's exactly-once invariant is observable from step one.
        Warmup writes land only in the reserved scratch page."""
        M, PP = self._cfg.max_slots, self._cfg.pages_per_seq
        trash = np.zeros((PP,), np.int32)
        with RecordEvent("generation::warmup"):
            for b in self._cfg.prefill_buckets:
                ids = np.zeros((1, b), np.int32)
                with self._dev_ctx():
                    # lint: allow(use-after-donate): donate_argnums covers only the NP pool args riding in the *splat; trash sits AFTER them (position NP+1) and is never donated — reused read-only across warmup prefills
                    out = self._prefill_jit(
                        self._W, *self._pools(), trash, ids, np.int32(1))
                self._set_pools(out[:-1])
                np.asarray(out[-1])
            args = self._step_arrays()
            out = self._decode_call(self._W, *self._pools(), *args)
            np.asarray(out[-2])
            self._set_pools(out[:-2])
            self._zero_pages([])

    # -- request intake ----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               timeout_ms: Optional[float] = None,
               do_sample: bool = False,
               temperature: float = 1.0) -> Future:
        """Enqueue one prompt (1-D int token ids); returns a Future of
        the full sequence (prompt + generated tokens, numpy int32; EOS,
        when hit, is included). Raises `EngineOverloaded` at
        max_queue_depth, `InvalidArgumentError`/`ResourceExhaustedError`
        for requests that could never run."""
        from . import EngineOverloaded
        with RecordEvent("generation::submit"):
            from ..framework.tensor import Tensor
            if isinstance(prompt_ids, Tensor):
                prompt_ids = prompt_ids.numpy()
            prompt = np.asarray(prompt_ids)
            if prompt.ndim != 1 or prompt.size < 1:
                raise InvalidArgumentError(
                    f"{self.name}: prompt_ids must be a non-empty 1-D "
                    f"token array, got shape {tuple(prompt.shape)}")
            if not np.issubdtype(prompt.dtype, np.integer):
                raise InvalidArgumentError(
                    f"{self.name}: prompt_ids must be integer token ids")
            prompt = prompt.astype(np.int32)
            max_new = int(self._cfg.max_new_tokens
                          if max_new_tokens is None else max_new_tokens)
            if max_new < 1:
                raise InvalidArgumentError("max_new_tokens must be >= 1")
            S = int(prompt.size)
            total = S + max_new
            if S > self._cfg.prefill_buckets[-1]:
                raise InvalidArgumentError(
                    f"{self.name}: prompt length {S} exceeds the largest "
                    f"prefill bucket {self._cfg.prefill_buckets[-1]}")
            if total > self._max_position:
                raise InvalidArgumentError(
                    f"{self.name}: {total} positions exceed "
                    f"max_position_embeddings={self._max_position}")
            if not self._cache.fits(total):
                raise ResourceExhaustedError(
                    f"{self.name}: {total} tokens need "
                    f"{self._cache.pages_needed(total)} pages but the "
                    f"pool holds {self._cache.usable_pages} "
                    f"(pages_per_seq={self._cache.pages_per_seq}); raise "
                    f"FLAGS_paged_num_pages or shrink the request")
            t = _now_ms()
            tmo = (self._cfg.request_timeout_ms if timeout_ms is None
                   else float(timeout_ms))
            reject_depth = None
            with self._cv:
                if self._closed:
                    raise UnavailableError(
                        f"{self.name}: engine is shut down")
                if len(self._queue) >= self._cfg.max_queue_depth:
                    reject_depth = len(self._queue)
                else:
                    req = _GenRequest(
                        prompt, max_new, eos_token_id, bool(do_sample),
                        float(temperature), Future(),
                        None if not tmo else t + tmo, t,
                        spans.start_gen(self.name))
                    self._req_seq += 1
                    req.ordinal = self._req_seq
                    self._queue.append(req)
                    monitor.stat_add("STAT_gen_queue_depth")
                    self._cv.notify_all()
            if reject_depth is not None:
                # audited OUTSIDE the lock: the JSONL sink's disk write
                # must not stall the step thread behind rejecting
                # clients, and rejections spike exactly under overload
                monitor.stat_add("STAT_gen_rejected")
                self._audit.audit("REJECT_QUEUE_FULL",
                                  queue_depth=reject_depth)
                self._audit.flush_sink()
                raise EngineOverloaded(
                    f"{self.name}: queue depth "
                    f"{self._cfg.max_queue_depth} reached; shed load "
                    f"or raise FLAGS_gen_max_queue_depth")
            monitor.stat_add("STAT_gen_requests")
            return req.future

    def generate(self, prompt_ids, **kw) -> np.ndarray:
        """Synchronous submit: blocks for this prompt's full sequence."""
        return self.submit(prompt_ids, **kw).result()

    # -- step loop ---------------------------------------------------------

    def _num_active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    def _loop(self):
        try:
            while True:
                with self._cv:
                    while (not self._queue and self._num_active() == 0
                           and not self._closed):
                        self._cv.wait()
                    if self._closed and self._abort:
                        self._evict_all(UnavailableError(
                            f"{self.name}: engine shut down"))
                        # flush the aborted/freed counts: the ring's
                        # sums must reconcile even on the abort exit
                        # (self._cv is an RLock-backed Condition, so
                        # re-acquiring inside is fine)
                        self._record_iteration()
                        self._flush_resolutions()
                        return
                    if (self._closed and not self._queue
                            and self._num_active() == 0):
                        return
                self._admit()
                self._expire_active()
                stepped = False
                if self._num_active():
                    self._step()
                    stepped = True
                self._record_iteration()
                # sink before resolutions: a caller woken by result()
                # may immediately read the JSONL — its own event must
                # already be on disk (no lock held here)
                self._audit.flush_sink()
                self._flush_resolutions()
                if not stepped:
                    with self._cv:
                        if (self._queue and self._num_active() == 0
                                and not self._abort):
                            # unadmittable head (page exhaustion): bounded
                            # wait so queued deadlines still expire
                            self._cv.wait(0.01)
        except BaseException as e:  # noqa: BLE001 — never hang submitters
            self._die(e)
            raise

    def _record_iteration(self):
        """One compact scheduler record per engine iteration (ISSUE 11):
        decision counts taken this pass, queue pressure, page-pool
        occupancy, prefill-vs-decode wall. Pure host bookkeeping — one
        ring append plus two histogram observes, no device syncs beyond
        what the iteration already did. The per-iteration counter dict
        is zeroed whether or not the ring is on, so an A/B flag flip
        can't leak one arm's counts into the other."""
        it, self._it = self._it, {
            "admitted": 0, "completed": 0, "expired": 0, "poisoned": 0,
            "aborted": 0, "freed": 0, "prefill_ms": 0.0,
            "decode_ms": 0.0}
        if self._step_log is None:
            return
        self._iters += 1
        with self._cv:
            depth = len(self._queue)
            oldest = (self._queue[0].t_enqueue_ms if self._queue
                      else None)
            live = self._num_active()
        rec = step_log.StepRecord(
            it=self._iters, step=self._steps_total,
            t=time.perf_counter(), live=live,
            queue_depth=depth,
            oldest_age_ms=round(_now_ms() - oldest, 3)
            if oldest is not None else 0.0,
            pages_in_use=self._cache.pages_in_use,
            free_pages=self._cache.free_pages,
            admitted=it["admitted"], completed=it["completed"],
            expired=it["expired"], poisoned=it["poisoned"],
            aborted=it["aborted"], freed=it["freed"],
            prefill_ms=round(it["prefill_ms"], 3),
            decode_ms=round(it["decode_ms"], 3))
        self._step_log.record(rec)

    def _resolve_later(self, fut, result=None, exc=None):
        """Hold a future's resolution until after this iteration's
        _record_iteration(): a caller woken by result() must observe a
        step ring / audit tail that already includes its own outcome —
        resolving mid-iteration let a reader hit /steps before the
        record landed and see counts that don't reconcile."""
        self._resolve_q.append((fut, result, exc))

    def _flush_resolutions(self):
        q, self._resolve_q = self._resolve_q, []
        for fut, result, exc in q:
            try:
                if exc is not None:
                    fut.set_exception(exc)
                else:
                    fut.set_result(result)
            except Exception:  # racing caller-side cancel pre-admission
                pass

    def _die(self, e: BaseException):
        try:
            # flush whatever the dying iteration already counted, so
            # the dump's step_log_tail reconciles with the audit tail
            self._record_iteration()
            self._flush_resolutions()
        except Exception:
            pass
        stranded = []
        with self._cv:
            self._closed = True
            self._death = e
            while self._queue:
                stranded.append(self._queue.popleft())
                monitor.stat_sub("STAT_gen_queue_depth")
            self._cv.notify_all()
        err = UnavailableError(f"{self.name}: generation engine died: "
                               f"{e!r}")
        active = [r for r in self._slots if r is not None]
        for req in active + stranded:
            try:
                req.future.set_exception(err)
            except Exception:
                pass
            self._audit.audit("ENGINE_DIED", rid=req.rid,
                              error=repr(e))
            slo.observe_request(self.name, ok=False)
        self._audit.flush_sink()
        flight_recorder.dump("gen_engine_death", {
            "engine": self.name, "error": repr(e),
            "stranded_requests": len(stranded),
            "active_sequences": len(active),
            "inflight_spans": [r.span.to_dict() for r in active
                               if r.span is not None][:64],
            # the scheduler state that LED here: last step-ring records
            # + the decision-audit tail with reason codes (ISSUE 11)
            "step_log_tail": (self._step_log.tail(32)
                              if self._step_log is not None else []),
            "audit_tail": self._audit.tail(64)})

    # -- admission ---------------------------------------------------------

    def _admit(self):
        """Admit queued requests while a slot AND worst-case pages are
        both free (FIFO, head-of-line blocking — later smaller requests
        never overtake, so admission latency stays predictable)."""
        while True:
            with self._cv:
                # whole-queue sweep, not just the head: a request queued
                # BEHIND a page-blocked head must still get its deadline
                # error on time (head-of-line blocking blocks admission,
                # never expiry)
                self._expire_queued()
                if not self._queue:
                    return
                req = self._queue[0]
                slot = next((i for i, r in enumerate(self._slots)
                             if r is None), None)
                if slot is None:
                    # once per request per cause: a full batch defers
                    # the head every iteration, and a per-iteration
                    # event would drown the audit ring in repeats
                    if "slots" not in req.defer_logged:
                        req.defer_logged.add("slots")
                        self._audit.audit(
                            "DEFER_SLOTS", rid=req.rid,
                            queue_depth=len(self._queue))
                    return
                total = int(req.prompt.size) + req.max_new
                if not self._cache.can_admit(total):
                    monitor.stat_add("STAT_gen_admit_blocked")
                    if "pages" not in req.defer_logged:
                        req.defer_logged.add("pages")
                        self._audit.audit(
                            "DEFER_PAGES", rid=req.rid,
                            need_pages=self._cache.pages_needed(total),
                            free_pages=self._cache.free_pages)
                    if not self._exhaust_dumped:
                        self._exhaust_dumped = True
                        flight_recorder.dump("gen_allocator_exhausted", {
                            "engine": self.name, "rid": req.rid,
                            "need_pages":
                                self._cache.pages_needed(total),
                            "cache": self._cache.stats(),
                            "queue_depth": len(self._queue),
                            "step_log_tail":
                                (self._step_log.tail(32)
                                 if self._step_log is not None else []),
                            "audit_tail": self._audit.tail(64)})
                    return
                self._queue.popleft()
                monitor.stat_sub("STAT_gen_queue_depth")
                if not req.future.set_running_or_notify_cancel():
                    self._audit.audit("CANCELLED", rid=req.rid)
                    continue
                req.slot = slot
                req.pt_row = self._cache.alloc(req.rid, total)
                self._slots[slot] = req
                self._it["admitted"] += 1
                self._audit.audit(
                    "ADMIT", rid=req.rid, slot=slot,
                    pages=self._cache.pages_needed(total),
                    queued_ms=round(_now_ms() - req.t_enqueue_ms, 3))
                if req.span is not None:
                    req.span.slot = slot
                    req.span.stamp("admitted")
            self._do_prefill(req)

    def _expire_queued(self):
        """Fail every expired request and drop every cancelled one from
        the WHOLE queue (position-independent); caller holds the lock."""
        t = _now_ms()
        live = deque()
        for req in self._queue:
            if req.deadline_ms is not None and t > req.deadline_ms:
                monitor.stat_sub("STAT_gen_queue_depth")
                monitor.stat_add("STAT_gen_timeouts")
                self._it["expired"] += 1
                self._audit.audit(
                    "EXPIRE_QUEUED", rid=req.rid,
                    queued_ms=round(t - req.t_enqueue_ms, 3))
                slo.observe_request(self.name, ok=False)
                self._resolve_later(req.future, exc=ExecutionTimeoutError(
                    f"{self.name}: request expired after "
                    f"{t - req.t_enqueue_ms:.1f}ms in queue"))
                continue
            if req.future.cancelled():
                monitor.stat_sub("STAT_gen_queue_depth")
                self._audit.audit("CANCELLED", rid=req.rid)
                continue
            live.append(req)
        self._queue = live

    def _bucket_for(self, S: int) -> int:
        for b in self._cfg.prefill_buckets:
            if b >= S:
                return b
        return self._cfg.prefill_buckets[-1]

    def _do_prefill(self, req: _GenRequest):
        """Run the request's prompt through the bucketed prefill program
        (writes its K/V pages), sample the first token, and mark the
        slot live — it joins the very next decode step. A poisoned
        request (non-finite logits — the pools came back valid) fails
        ONLY this request and returns its pages zeroed; an exception
        from the jitted call itself is engine-fatal, because the pools
        were DONATED into it and may already be consumed — touching
        them again (even to zero this request's pages) would
        dereference deleted buffers (same contract as a decode-step
        exception)."""
        S = int(req.prompt.size)
        bucket = self._bucket_for(S)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :S] = req.prompt
        t0 = _now_ms()
        with RecordEvent(f"generation::prefill[b={bucket}]"):
            with self._dev_ctx():
                out = self._prefill_jit(
                    self._W, *self._pools(), req.pt_row, ids,
                    np.int32(S))
            self._set_pools(out[:-1])
            lg = np.asarray(out[-1])
        self._it["prefill_ms"] += _now_ms() - t0
        if not np.all(np.isfinite(lg)):
            monitor.stat_add("STAT_gen_poisoned")
            self._it["poisoned"] += 1
            self._audit.audit("POISON_PREFILL", rid=req.rid,
                              bucket=bucket)
            slo.observe_request(self.name, ok=False)
            flight_recorder.dump("gen_poisoned_sequence", {
                "engine": self.name, "rid": req.rid, "stage": "prefill",
                "bucket": bucket, "error": "non-finite prefill logits",
                "step_log_tail": (self._step_log.tail(32)
                                  if self._step_log is not None else []),
                "audit_tail": self._audit.tail(64)})
            self._release(req)
            self._resolve_later(req.future, exc=FatalError(
                f"{self.name}: non-finite prefill logits for request "
                f"{req.rid} (poisoned prompt or weights)"))
            return
        self._prefills_total += 1
        monitor.stat_add("STAT_gen_prefills")
        tok = self._sample_host(req, lg)
        req.toks.append(tok)
        req.next_pos = S
        self._tokens_total += 1
        monitor.stat_add("STAT_gen_tokens")
        if req.span is not None:
            req.span.stamp("prefilled")
            req.span.stamp("first_token")
            req.span.stamp("last_token")
        if self._finished(req, tok):
            self._complete(req)

    def _sample_host(self, req: _GenRequest, logits: np.ndarray) -> int:
        """First-token sampling on host (prefill returns logits; decode
        samples in-graph). Greedy is np.argmax — first-max ties, same
        as jnp.argmax, so greedy parity with generate() holds."""
        if not req.do_sample:
            return int(np.argmax(logits))
        lg = logits / max(req.temperature, 1e-6)
        if self._cfg.top_k:
            kth = np.sort(lg)[-int(self._cfg.top_k)]
            lg = np.where(lg < kth, -1e30, lg)
        # engine-local ordinal, NOT the process-global rid: two engines
        # with the same config/seed must sample identical streams
        r = np.random.RandomState(
            (self._cfg.seed * 1000003 + req.ordinal) % (2 ** 31))
        g = -np.log(-np.log(r.uniform(1e-12, 1.0, lg.shape)))
        return int(np.argmax(lg + g))

    # -- decode step -------------------------------------------------------

    def _step_arrays(self):
        M, PP = self._cfg.max_slots, self._cfg.pages_per_seq
        toks = np.zeros((M,), np.int32)
        pos = np.zeros((M,), np.int32)
        active = np.zeros((M,), bool)
        temps = np.ones((M,), np.float32)
        smask = np.zeros((M,), bool)
        pt = np.zeros((M, PP), np.int32)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            active[i] = True
            toks[i] = req.toks[-1]
            pos[i] = req.next_pos
            temps[i] = req.temperature
            smask[i] = req.do_sample
            pt[i] = req.pt_row
        key = self._step_key()
        return pt, toks, pos, active, temps, smask, key

    def _step_key(self):
        import jax
        if self._base_key is None:
            self._base_key = jax.random.PRNGKey(self._cfg.seed)
        return jax.random.fold_in(self._base_key, self._steps_total)

    def _step(self):
        """ONE engine step: every live sequence advances one token
        through the single compiled decode program (inactive slots are
        masked into the reserved scratch page). The np.asarray below is
        the step's only host sync."""
        if self._pre_step_hook is not None:
            self._pre_step_hook(self)
        args = self._step_arrays()
        t0 = _now_ms()
        with RecordEvent(f"generation::step[m={self._cfg.max_slots}]"):
            out = self._decode_call(self._W, *self._pools(), *args)
            nxt = np.asarray(out[-2])
            bad = np.asarray(out[-1])
        self._set_pools(out[:-2])
        self._it["decode_ms"] += _now_ms() - t0
        self._steps_total += 1
        monitor.stat_add("STAT_gen_steps")
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if bad[i]:
                # poison isolation: only THIS sequence fails; its pages
                # are zeroed before reuse so the NaN cannot reach the
                # next owner's masked attention
                monitor.stat_add("STAT_gen_poisoned")
                self._it["poisoned"] += 1
                self._audit.audit("POISON_DECODE", rid=req.rid, slot=i,
                                  generated=len(req.toks))
                slo.observe_request(self.name, ok=False)
                flight_recorder.dump("gen_poisoned_sequence", {
                    "engine": self.name, "rid": req.rid, "stage": "decode",
                    "slot": i, "generated": len(req.toks),
                    "error": "non-finite decode logits",
                    "step_log_tail": (self._step_log.tail(32)
                                      if self._step_log is not None
                                      else []),
                    "audit_tail": self._audit.tail(64)})
                self._evict(req, FatalError(
                    f"{self.name}: sequence {req.rid} produced "
                    f"non-finite logits at step {len(req.toks)}"))
                continue
            tok = int(nxt[i])
            req.toks.append(tok)
            req.next_pos += 1
            self._tokens_total += 1
            monitor.stat_add("STAT_gen_tokens")
            if req.span is not None:
                req.span.stamp("last_token")
            if self._finished(req, tok):
                self._complete(req)

    def _finished(self, req: _GenRequest, tok: int) -> bool:
        return ((req.eos is not None and tok == req.eos)
                or len(req.toks) >= req.max_new)

    def _expire_active(self):
        """Per-step deadline enforcement: an expired sequence cancels
        mid-decode — pages freed the same step, only its future fails."""
        t = _now_ms()
        for req in list(self._slots):
            if req is None or req.deadline_ms is None:
                continue
            if t > req.deadline_ms:
                monitor.stat_add("STAT_gen_timeouts")
                self._it["expired"] += 1
                self._audit.audit(
                    "EXPIRE_DECODE", rid=req.rid, slot=req.slot,
                    generated=len(req.toks),
                    age_ms=round(t - req.t_enqueue_ms, 3))
                slo.observe_request(self.name, ok=False)
                self._evict(req, ExecutionTimeoutError(
                    f"{self.name}: request {req.rid} expired after "
                    f"{t - req.t_enqueue_ms:.1f}ms with "
                    f"{len(req.toks)}/{req.max_new} tokens decoded "
                    f"(deadlines are whole-request; partial streams are "
                    f"not delivered)"))

    # -- completion / eviction ---------------------------------------------

    def _release(self, req: _GenRequest):
        """Return the request's slot + pages (pages zeroed on device)."""
        pages = self._cache.free(req.rid)
        if pages:
            self._zero_pages(pages)
            self._exhaust_dumped = False  # pages freed: new episode
        if req.slot is not None and self._slots[req.slot] is req:
            self._slots[req.slot] = None
            self._it["freed"] += 1
        with self._cv:
            self._cv.notify_all()

    def _complete(self, req: _GenRequest):
        self._release(req)
        out = np.concatenate([req.prompt,
                              np.asarray(req.toks, np.int32)])
        t_done = _now_ms()
        self._hist.observe(t_done - req.t_enqueue_ms)
        if req.deadline_ms is not None and t_done > req.deadline_ms:
            # finished the same instant it expired: honor the deadline
            # (a timeout, NOT a completion — the two counters partition
            # the finished-naturally outcomes)
            monitor.stat_add("STAT_gen_timeouts")
            self._it["expired"] += 1
            self._audit.audit("EXPIRE_LATE", rid=req.rid,
                              generated=len(req.toks))
            slo.observe_request(self.name, ok=False)
            self._resolve_later(req.future, exc=ExecutionTimeoutError(
                f"{self.name}: request expired after "
                f"{t_done - req.t_enqueue_ms:.1f}ms"))
            return
        # delivery cannot fail: _admit claimed the future via
        # set_running_or_notify_cancel, so a caller-side cancel is no
        # longer possible — count now, resolve after the ring record
        self._resolve_later(req.future, result=out)
        monitor.stat_add("STAT_gen_completions")  # delivered results
        self._it["completed"] += 1
        self._audit.audit(
            "COMPLETE_EOS" if (req.eos is not None
                               and req.toks
                               and req.toks[-1] == req.eos)
            else "COMPLETE_MAX_NEW",
            rid=req.rid, generated=len(req.toks),
            e2e_ms=round(t_done - req.t_enqueue_ms, 3))
        slo.observe_request(self.name, ok=True)
        if req.span is not None:
            req.span.stamp("resolved")
            req.span.finish(len(req.toks))

    def _evict(self, req: _GenRequest, err: BaseException):
        """Cancel a LIVE sequence mid-decode: free + zero its pages,
        fail only its own future."""
        self._release(req)
        monitor.stat_add("STAT_gen_evictions")
        self._resolve_later(req.future, exc=err)

    def _evict_all(self, err: BaseException):
        for req in list(self._slots):
            if req is not None:
                # deliberate operator action (shutdown/abort): audited
                # but NOT an SLO error — a drain must not burn the
                # error budget of the replicas still serving
                self._it["aborted"] += 1
                self._audit.audit("EVICT_SHUTDOWN", rid=req.rid,
                                  generated=len(req.toks))
                self._evict(req, err)

    # -- lifecycle / introspection -----------------------------------------

    def stats(self) -> dict:
        """Engine snapshot: per-slot state, page-pool occupancy + KV
        introspection, the exact compile ledger, token/step totals, and
        the TTFT/TPOT + end-to-end latency histograms."""
        with self._cv:
            depth = len(self._queue)
            slots = [{"slot": i,
                      "rid": r.rid if r is not None else None,
                      "generated": len(r.toks) if r is not None else 0,
                      "prompt_len": int(r.prompt.size)
                      if r is not None else 0}
                     for i, r in enumerate(self._slots)]
            slot_of = {r.rid: i for i, r in enumerate(self._slots)
                       if r is not None}
            ledger = dict(self._ledger)
            steps, prefills, tokens = (self._steps_total,
                                       self._prefills_total,
                                       self._tokens_total)
        return {
            "slots": slots,
            "queue_depth": depth,
            "pages": self._cache.stats(),
            "kv": self._kv_introspection(slot_of),
            "compiles": ledger,
            "steps": steps,
            "prefills": prefills,
            "tokens": tokens,
            "step_log": {
                "enabled": self._step_log is not None,
                "recorded": (self._step_log.recorded
                             if self._step_log is not None else 0),
                "audit_events": self._audit.recorded,
            },
            "latency_ms": self._hist.snapshot(),
            "ttft_ms": monitor.histogram("ttft_ms").snapshot(),
            "tpot_ms": monitor.histogram("tpot_ms").snapshot(),
        }

    def _kv_introspection(self, slot_of=None) -> dict:
        """`stats()["kv"]`: pool stats + watermarks, the per-sequence
        page-ownership map (joined to decode slots), and the admission-
        headroom estimate for this engine's representative request
        shapes — one `can_admit` count per (prefill bucket + default
        max-new) total, the per-replica pressure surface the router
        tier compares (ISSUE 11)."""
        out = dict(self._cache.stats())
        owners = self._cache.owners()
        if slot_of is None:
            with self._cv:
                slot_of = {r.rid: i for i, r in enumerate(self._slots)
                           if r is not None}
        out["owners"] = [
            {"rid": rid, "slot": slot_of.get(rid), "pages": pages}
            for rid, pages in sorted(owners.items())]
        shapes = {b + self._cfg.max_new_tokens
                  for b in self._cfg.prefill_buckets}
        out["admit_headroom"] = {
            str(tokens): n
            for tokens, n in sorted(
                self._cache.headroom(sorted(shapes)).items())}
        return out

    def health(self) -> dict:
        """`/readyz` verdict, same shape as InferenceEngine.health() so
        the router tier drains generation replicas identically."""
        with self._cv:
            depth = len(self._queue)
            draining = self._closed
            live = int(getattr(self, "_thread", None) is not None
                       and self._thread.is_alive() and self._death is None)
            slots_free = sum(1 for r in self._slots if r is None)
        limit = self._cfg.max_queue_depth
        warmed = self._warmed
        if draining:
            reason = "draining"
        elif not warmed:
            reason = "warming up"
        elif not live:
            reason = "step loop dead"
        elif depth >= limit:
            reason = "queue at rejection threshold"
        else:
            # SLO folding (ISSUE 11): with FLAGS_slo_max_burn_rate set,
            # a replica burning its error budget too fast reports
            # not-ready so the router sheds load BEFORE the budget is
            # gone — the pre-emptive drain surface
            reason = slo.shed_verdict(self.name) or "ok"
        return {"ready": reason == "ok", "reason": reason,
                "warmup_complete": warmed, "draining": draining,
                "live_lanes": live, "queue_depth": depth,
                "queue_limit": limit, "slots_free": slots_free,
                "slots": self._cfg.max_slots}

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None):
        """Stop intake; by default every queued + live sequence finishes
        before the step loop exits. drain=False fails pending futures
        fast (live sequences are evicted, pages freed)."""
        dropped = []
        with self._cv:
            self._closed = True
            if not drain:
                self._abort = True
                while self._queue:
                    req = self._queue.popleft()
                    monitor.stat_sub("STAT_gen_queue_depth")
                    dropped.append(req)
                    try:
                        req.future.set_exception(UnavailableError(
                            f"{self.name}: engine shut down"))
                    except Exception:
                        pass
            self._cv.notify_all()
        for req in dropped:
            # audited OUTSIDE the lock (disk sink); queued drops get
            # their own code so the step ring's aborted count still
            # reconciles exactly with the live EVICT_SHUTDOWN events
            self._audit.audit("EVICT_SHUTDOWN_QUEUED", rid=req.rid,
                              queued_ms=round(_now_ms()
                                              - req.t_enqueue_ms, 3))
        t = getattr(self, "_thread", None)
        if t is not None:
            t.join(timeout_s)
        exporter.unregister_engine(self)
        if self._step_log is not None:
            step_log.unregister(self._step_log)
        self._audit.close()
        slo.forget(self.name)
        if getattr(self, "_owns_metrics_server", False) \
                and self.metrics_server is not None:
            self.metrics_server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
