"""Content-hash prefix cache over the paged KV pools (ISSUE 12).

Real chat/agent traffic is thousands of requests sharing one system
prompt; without reuse every request prefills it from scratch.
RadixAttention's insight, page-granular: index already-computed prompt
K/V by a **content-hash block chain** — one node per FULL page of
prompt tokens, keyed by `blake2b(parent_digest + page_token_ids)` — so
a chain digest commits to every token before it and two prompts share
cached pages exactly as far as their token streams agree. A request
whose prompt walks the chain maps those pages READ-ONLY
(`PagedKVCache.alloc_shared`) and prefills only the tail; vLLM's
copy-on-write covers the one divergent-write case (a full-prompt match
must recompute its last position's logits, so the page holding it is
split private before the tail prefill writes through it).

Ownership model (the refcount substrate lives in `kv_cache.py`):

- Registration (`register`, after a successful prefill) takes a cache
  reference on each full prompt page (`cache_hold`) — the chain
  survives its producer sequence's free, content preserved, NOT zeroed
  (zero-on-free defers until refcount 0).
- A chain page shared by live sequences is not reclaimable; once only
  the index holds it (refcount 1) it is *evictable* and counts toward
  `can_admit`/`headroom` so admission capacity stays truthful.
- Eviction (`evict`, called by the engine BEFORE alloc when the free
  list alone is short) walks least-recently-used LEAF nodes — children
  before parents, so a surviving node is always reachable from the
  root — releasing the index reference; pages freed NOW (refcount 0)
  are returned for the engine's zero-on-free scatter, pages a live
  sequence still shares zero later through that sequence's free.

Tiered demotion (ISSUE 18): with a `HostTier` attached
(`attach_tier`), a chain node has THREE states — HBM (`page` is a
physical page id, cache-held), host (`page is None`, raw content in
the host store under the node's digest), gone (absent from the index).
Eviction *demotes* instead of discarding: the engine's gather callback
pulls the page's raw bytes (+ int8 scale rows) off-device into the
host store, the HBM page is released/zeroed exactly as before, and the
node survives host-state. `lookup_tiered` walks THROUGH host nodes —
the admission promotes the matched host run back into fresh HBM pages
(upload overlapped with its tail prefill) via `consume_promoted`,
after which the post-prefill `register` re-creates the nodes bound to
the request's pages. Along any root path states run HBM* then host*
(demotion takes the deepest HBM nodes first; `register` re-binds any
host node it walks), so victim selection treats "no HBM children" as
leaf-ness and host nodes are never victims themselves. Dropping a host
node cascades over its (necessarily host) descendants —
demote-of-demoted is the final eviction.

Single-writer like the allocator: the engine's step thread owns every
mutation (lookup/register/evict/demote/promote); `stats()` takes
GIL-consistent snapshots for scraper threads.
"""
from __future__ import annotations

import hashlib
import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework import monitor
from ..framework.flags import flag
from .kv_cache import PagedKVCache

__all__ = ["PrefixCache", "chain_digests"]

_ROOT = b"paged-prefix-root"


def chain_digests(token_ids: np.ndarray, page_size: int) -> List[bytes]:
    """The blake2b chain digests of every FULL page of `token_ids` —
    digest i commits to tokens [0, (i+1)*page_size), so equal digests
    mean equal token streams up to that page boundary.

    This is THE digest implementation: `PrefixCache` (the engine's
    cache index) and the router tier's affinity hashing both call it,
    so a prompt hashes identically on every replica and the two sides
    cannot drift. Content-only — no engine, device, or pool state is
    mixed in."""
    P = int(page_size)
    toks = np.ascontiguousarray(np.asarray(token_ids, np.int32))
    out, parent = [], _ROOT
    for i in range(int(toks.size) // P):
        h = hashlib.blake2b(parent, digest_size=16)
        h.update(toks[i * P:(i + 1) * P].tobytes())
        parent = h.digest()
        out.append(parent)
    return out


class _Node:
    """One full prompt page in the chain tree."""

    __slots__ = ("key", "parent", "page", "children", "tick")

    def __init__(self, key: bytes, parent: Optional[bytes], page: int,
                 tick: int):
        self.key = key
        self.parent = parent        # parent digest (None at depth 0)
        self.page = page            # physical page id in the pools, or
                                    # None = demoted to the host tier
                                    # (content under `key` in HostTier)
        self.children: set = set()  # child digests
        self.tick = tick            # LRU clock (max of hits on the path)


class PrefixCache:
    """Block-chain index of cached prompt-prefix pages for ONE engine's
    `PagedKVCache` (the engine's step thread is the only writer)."""

    def __init__(self, kv: PagedKVCache, engine: str = "generation",
                 max_pages: Optional[int] = None):
        self._kv = kv
        self.engine = engine
        self._nodes: Dict[bytes, _Node] = {}
        self._tick = itertools.count(1)
        # byte budget as a page-count cap (ISSUE 14): register() evicts
        # eagerly back to it, so the index can't grow without bound
        # between admissions that happen to run short of free pages;
        # 0/None = unbounded (evict-on-demand only, the ISSUE 12 shape)
        self.max_pages = int(flag("FLAGS_gen_prefix_cache_max_pages")
                             if max_pages is None else max_pages)
        # counted per ADMISSION via note_admitted, never per lookup — a
        # deferred head re-looks-up every engine iteration
        self.hits = 0           # admissions that matched >= 1 cached page
        self.misses = 0         # admissions that matched nothing
        self.hit_tokens = 0     # prompt tokens served from cached pages
        self.evictions = 0      # chain nodes evicted from HBM (LRU;
                                # includes demotions — the page left
                                # HBM either way)
        # host demotion tier (ISSUE 18; attach_tier wires all three —
        # None keeps the two-state PR 12 semantics exactly)
        self._tier = None       # serving.kv_tier.HostTier
        self._gather = None     # page -> (k, v, ks, vs) | None
        self._audit = None      # engine AuditLog (KV_DEMOTE/TIER_EVICT)
        self._protect: set = set()  # digests an in-flight admission
                                    # matched host-side: never tier-evict

    # -- host tier (ISSUE 18) ----------------------------------------------

    def attach_tier(self, tier, gather, audit=None) -> None:
        """Enable the host demotion tier: `tier` is the bounded
        `HostTier` store, `gather` the engine's off-device page gather
        (`page -> (k, v, ks, vs)` raw numpy, or None when the gather
        failed / the `kv_tier.demote_gather` failpoint fired — the
        eviction then proceeds plain), `audit` the engine's AuditLog
        for KV_DEMOTE / KV_TIER_EVICT events."""
        self._tier = tier
        self._gather = gather
        self._audit = audit

    def protect(self, digests) -> None:
        """Shield an admission's matched host run from tier eviction
        until `unprotect` — between `lookup_tiered` and
        `consume_promoted` the SAME admission may demote eviction
        victims into the tier, and the LRU must not cannibalize the
        entries it is about to promote."""
        self._protect = set(digests)

    def unprotect(self) -> None:
        self._protect = set()

    def consume_promoted(self, digests: List[bytes]):
        """Move an admission's matched host run out of the tier
        (promotion): pops each digest's `HostEntry` (the admission now
        owns the content — it re-uploads into its own fresh pages) and
        drops the nodes from the index; the chain re-registers bound to
        the request's pages after its prefill, exactly like a fresh
        one. Orphaned host descendants beyond the run cascade out
        (final eviction). Returns `(entries, cascade_dropped)`."""
        entries = [self._tier.pop(d) for d in digests]
        dropped = self._drop_host_node(digests[0], pop_entry=False)
        return entries, dropped

    def _drop_host_node(self, digest: bytes, pop_entry: bool) -> int:
        """Remove one host-state node and every descendant (all host by
        the HBM*-then-host* path invariant), popping their tier entries
        as final evictions; returns how many entries were dropped.
        `pop_entry=False` for a root whose entry the caller already
        consumed (tier LRU eviction / promotion)."""
        dropped = 0
        node = self._nodes.pop(digest, None)
        if pop_entry and self._tier is not None:
            if self._tier.pop(digest, final=True) is not None:
                dropped += 1
        if node is None:
            return dropped
        if node.parent is not None and node.parent in self._nodes:
            self._nodes[node.parent].children.discard(digest)
        for c in list(node.children):
            child = self._nodes.get(c)
            if child is not None and child.page is None:
                dropped += self._drop_host_node(c, pop_entry=True)
        return dropped

    # -- hashing -----------------------------------------------------------

    def digests(self, prompt: np.ndarray) -> List[bytes]:
        """`chain_digests` at this cache's page size — `lookup` and
        `register` key the index through this single implementation."""
        return chain_digests(prompt, self._kv.page_size)

    # -- lookup / register -------------------------------------------------

    def lookup(self, prompt: np.ndarray) -> Tuple[List[bytes], List[int]]:
        """(digests_of_all_full_pages, matched_page_ids): the longest
        cached chain this prompt's leading full pages walk. Touches the
        matched path's LRU clock but counts nothing — the engine calls
        `note_admitted` once per ADMITTED request (a deferred head
        re-looks-up every iteration and must not inflate the hit
        counters). The caller must `pin` the matched pages before any
        eviction can run (a hit is only a plan until the pages are
        referenced)."""
        digests = self.digests(prompt)
        pages: List[int] = []
        tick = next(self._tick)
        for d in digests:
            node = self._nodes.get(d)
            if node is None or node.page is None:
                break   # gone, or demoted to host: not an HBM hit
            node.tick = tick
            pages.append(node.page)
        return digests, pages

    def lookup_tiered(self, prompt: np.ndarray):
        """Promote-aware lookup (ISSUE 18): like `lookup`, but the walk
        continues THROUGH host-state nodes. Returns
        `(digests, hbm_pages, host_digests)` — the leading HBM run
        (map read-only, as ever) followed by the contiguous host run
        (the admission promotes these via `consume_promoted`), stopping
        at the first gone digest. Same counting contract as `lookup`:
        touches LRU clocks, counts nothing."""
        digests = self.digests(prompt)
        pages: List[int] = []
        host: List[bytes] = []
        tick = next(self._tick)
        for d in digests:
            node = self._nodes.get(d)
            if node is None:
                break
            node.tick = tick
            if node.page is not None:
                if host:
                    break   # HBM after host: impossible by the path
                            # invariant — stop defensively
                pages.append(node.page)
            else:
                if self._tier is None or d not in self._tier:
                    break   # host node without an entry: defensive
                host.append(d)
        return digests, pages, host

    def note_admitted(self, hit_tokens: int, host_tokens: int = 0) -> None:
        """Count one admission's cache outcome: `hit_tokens` prompt
        tokens served from cached pages (0 = a miss), of which
        `host_tokens` came up from the host tier (promotion)."""
        if hit_tokens > 0:
            self.hits += 1
            self.hit_tokens += int(hit_tokens)
            monitor.stat_add("STAT_prefix_hits")
            monitor.stat_add("STAT_prefix_hit_tokens", int(hit_tokens))
        else:
            self.misses += 1
        if host_tokens > 0 and self._tier is not None:
            self._tier.note_hit()

    def register(self, digests: List[bytes], pt_row) -> List[int]:
        """Index a freshly prefilled (or freshly decoded — generated
        suffixes register at completion, ISSUE 14) sequence's full
        pages (called by the step thread after the K/V landed).
        Existing nodes are touched, new nodes take a cache reference on
        their page (`cache_hold`). A full-match CoW split never
        re-registers: its node already exists and keeps the ORIGINAL
        page — the private copy belongs to the sequence alone.

        With a `max_pages` budget set, registration that pushes the
        cached-page count over it eagerly LRU-evicts OTHER chains back
        to budget (the just-registered chain is excluded — evicting
        what was registered a microsecond ago would be pure thrash).
        Returns the page ids freed by that eviction (refcount hit 0) —
        the engine zeroes them before reuse, exactly the evict()
        contract."""
        added = 0
        tick = next(self._tick)
        parent: Optional[bytes] = None
        own: List[int] = []
        for i, d in enumerate(digests):
            node = self._nodes.get(d)
            if node is None:
                page = int(pt_row[i])
                self._kv.cache_hold([page])
                node = _Node(d, parent, page, tick)
                self._nodes[d] = node
                if parent is not None and parent in self._nodes:
                    self._nodes[parent].children.add(d)
                added += 1
            else:
                if node.page is None:
                    # host-state node walked by a fresh prefill (the
                    # admission cold-prefilled past it — e.g. after a
                    # promotion abandon): re-bind to the producer's
                    # page (identical content) and drop the host copy
                    # — at most ONE copy per digest, ever
                    page = int(pt_row[i])
                    self._kv.cache_hold([page])
                    node.page = page
                    if self._tier is not None:
                        self._tier.pop(d)   # content is back in HBM
                    added += 1
                node.tick = tick
            own.append(node.page)
            parent = d
        freed: List[int] = []
        if added and self.max_pages:
            # eager budget enforcement: shrink the CACHED page count
            # back to the cap (a live-shared victim releases the index
            # reference without freeing bytes NOW — it still leaves the
            # budget, and its page returns through the sharer's free)
            refs = self._kv.refcounts()
            exclude = set(own)
            while (len(self._kv.cached_pages()) > self.max_pages
                   and self._nodes):
                victim = self._pick_victim(refs, exclude)
                if victim is None:
                    break
                freed.extend(self._evict_node(victim, refs))
        if added or freed:
            monitor.stat_set("STAT_prefix_cached_pages",
                             len(self._kv.cached_pages()))
        return freed

    # -- eviction ----------------------------------------------------------

    def evict(self, need_pages: int, exclude=()) -> List[int]:
        """Release least-recently-used LEAF chains until `need_pages`
        pages have actually returned to the free list (or nothing more
        can be evicted). Returns the freed page ids — the engine zeroes
        them before reuse (this is the deferred zero-on-free point for
        cached pages).

        Victim policy: prefer leaves whose page ONLY the index holds
        (refcount 1 — the ones that actually free bytes); a leaf a live
        sequence still shares is victimized only when no freeable leaf
        exists, because a refcount-1 ancestor can be blocked behind it
        (children must leave the index before their parent, or the
        survivor would be unreachable from the root). `exclude` pages
        (the admitting request's just-matched — and pinned — chain) are
        never victimized: evicting them would force a needless re-prefill
        and, on a full-prompt match, re-register the chain against the
        CoW private copy."""
        refs = self._kv.refcounts()
        exclude = set(exclude)
        freed: List[int] = []
        while len(freed) < need_pages and self._nodes:
            victim = self._pick_victim(refs, exclude)
            if victim is None:
                break
            freed.extend(self._evict_node(victim, refs))
        monitor.stat_set("STAT_prefix_cached_pages",
                         len(self._kv.cached_pages()))
        return freed

    def _pick_victim(self, refs: Dict[int, int],
                     exclude: set) -> Optional[_Node]:
        """The next LRU LEAF to evict: prefer leaves whose page only
        the index holds (refcount 1 — the ones that actually free
        bytes); fall back to the LRU shared leaf, which frees nothing
        itself but exposes the freeable pages behind it (children must
        leave the index before their parent). None when every leaf is
        excluded. Host-state nodes are never victims (nothing in HBM
        to free) and never BLOCK one either — leaf-ness means "no HBM
        children", so a chain whose tail already demoted keeps
        draining parent-ward."""
        leaves = [n for n in self._nodes.values()
                  if n.page is not None and n.page not in exclude
                  and not any(
                      c is not None and c.page is not None
                      for c in (self._nodes.get(ck)
                                for ck in n.children))]
        if not leaves:
            return None
        victim = min((n for n in leaves if refs.get(n.page) == 1),
                     key=lambda n: n.tick, default=None)
        if victim is None:
            victim = min(leaves, key=lambda n: n.tick)
        return victim

    def _evict_node(self, victim: _Node,
                    refs: Dict[int, int]) -> List[int]:
        """Release one node's HBM page; returns the pages freed NOW
        (refcount 0). With a host tier attached the node DEMOTES —
        content gathered off-device into the store, node survives
        host-state — unless the gather fails (failpoint / reject), in
        which case the node drops exactly as before (and any host
        descendants it stranded cascade out)."""
        page = victim.page
        demoted = False
        if self._tier is not None and self._gather is not None:
            data = self._gather(page)
            if data is not None:
                from .kv_tier import HostEntry
                stored, evicted = self._tier.put(
                    victim.key, HostEntry(*data), protect=self._protect)
                if evicted:
                    dropped = 0
                    for d in evicted:
                        dropped += 1
                        dropped += self._drop_host_node(
                            d, pop_entry=False)
                    if self._audit is not None:
                        self._audit.audit("KV_TIER_EVICT", None,
                                          entries=dropped)
                demoted = stored
        if demoted:
            victim.page = None   # node survives, host-state
            if self._audit is not None:
                self._audit.audit("KV_DEMOTE", None, page=page)
        else:
            del self._nodes[victim.key]
            if victim.parent is not None and victim.parent in self._nodes:
                self._nodes[victim.parent].children.discard(victim.key)
            # a failed demotion strands this node's host descendants
            # (unreachable from any future walk): cascade them out
            dropped = 0
            for c in list(victim.children):
                child = self._nodes.get(c)
                if child is not None and child.page is None:
                    dropped += self._drop_host_node(c, pop_entry=True)
            if dropped and self._audit is not None:
                self._audit.audit("KV_TIER_EVICT", None, entries=dropped)
        out = self._kv.cache_release([page])
        refs.pop(page, None)
        self.evictions += 1
        monitor.stat_add("STAT_prefix_evictions")
        return out

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._nodes)

    def stats(self) -> dict:
        """Scraper-safe snapshot (counters are GIL-atomic ints)."""
        out = {
            "enabled": True,
            "engine": self.engine,
            "max_pages": self.max_pages,
            "nodes": len(self._nodes),
            "cached_pages": len(self._kv.cached_pages()),
            "evictable_pages": self._kv.evictable_pages,
            "hits": self.hits,
            "misses": self.misses,
            "hit_tokens": self.hit_tokens,
            "evictions": self.evictions,
            # host tier (ISSUE 18) — zeros when no tier is attached so
            # report tooling reads one shape either way
            "tier_enabled": self._tier is not None,
            "host_bytes": 0,
            "host_entries": 0,
            "host_nodes": 0,
            "demotions": 0,
            "promotions": 0,
            "tier_hits": 0,
            "tier_evictions": 0,
            "tier_abandons": 0,
            "tier_hit_rate": 0.0,
        }
        if self._tier is not None:
            t = self._tier.stats()
            out["host_bytes"] = t["host_bytes"]
            out["host_entries"] = t["entries"]
            out["host_nodes"] = sum(
                1 for n in list(self._nodes.values()) if n.page is None)
            out["demotions"] = t["demotions"]
            out["promotions"] = t["promotions"]
            out["tier_hits"] = t["hits"]
            out["tier_evictions"] = t["evictions"]
            out["tier_abandons"] = t["abandons"]
            out["tier_hit_rate"] = round(
                t["hits"] / max(1, self.hits + self.misses), 4)
        return out
