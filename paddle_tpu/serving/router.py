"""The router tier: one front door over N self-healing replicas
(ISSUE 17).

Orca's split, fleet-scale: the engine decides per-STEP (continuous
batching), the router decides per-REQUEST. Each replica is an
`EngineSupervisor`-wrapped `GenerationEngine` — already self-healing
(PR 14), already warm-startable (PR 15), already exposing drain and
pressure surfaces (PR 11) — so the router stays thin: placement policy
plus the same `submit()`/`submit_stream()`/`generate()` surface, and
everything below it keeps its existing exactly-once semantics.

Placement is **prefix-affinity first** (SGLang's RadixAttention
insight, lifted above the replica): the blake2b chain digests of a
prompt's leading FULL pages (`prefix_cache.chain_digests` — the same
implementation the engine's cache index uses, so the two sides cannot
drift) are content-only and therefore replica-independent. The router
keeps a bounded per-replica LRU sketch of the chains it has placed;
an incoming prompt steers to the replica holding its LONGEST chain —
session stickiness for agent loops (turn N+1's prompt extends turn N's,
so its digests re-match) with ZERO session state in the router: lose
the sketch and you lose warmth, never correctness. Ties and misses fall
back to least-pressure balancing on a cached per-replica
`pressure()` snapshot: KV headroom at the request's covering shape,
then queue depth, then oldest-queue-age, with a rotating tiebreak so
equal replicas alternate. `affinity=False` (FLAGS_router_affinity)
degrades placement to pure round-robin — the bench A/B arm.

Health folds in the PR 11/14 surfaces: a replica whose `health()` says
not-ready — SLO fast-window burn past FLAGS_slo_max_burn_rate, breaker
open, draining, queue at rejection threshold — is DRAINED: no new
placements while its live streams finish untouched. A request stranded
by a replica death never reaches the router at all: the replica's own
supervisor replays it exactly-once under the existing
retry-budget/typed-failure semantics. The router only re-routes
failures raised AT placement time (breaker open, shutdown, queue-full
backpressure), when nothing has been delivered yet — so streams stay
exactly-once by construction.

Every placement decision is one event in the router's own closed-
vocabulary audit ring (ROUTE_AFFINITY / ROUTE_LEAST_PRESSURE /
ROUTE_DRAIN / ROUTE_REROUTE) and the router registers with the
exporter like any engine: `/readyz` is ready while >= 1 replica is
placeable, `/stats` carries placements, sketches, and a bounded
per-replica pressure timeline (`tools/router_report.py` renders both).

Locking: one plain lock around the sketch/snapshot/pick state, held
only for host bookkeeping — never across a replica call. Replica
`pressure()` reads are lock-free on the engine side by design
(step-thread-published snapshot), so router polling cannot contend any
step loop.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework import monitor
from ..framework.errors import (InvalidArgumentError, ResourceExhaustedError,
                                UnavailableError)
from ..framework.flags import flag
from ..profiler import audit, exporter, trace_context, tracer
from .generation import GenerationConfig, TokenStream
from .prefix_cache import chain_digests
from .supervisor import EngineSupervisor

__all__ = ["Router"]


class _Replica:
    """Router-side state for one supervised replica."""

    __slots__ = ("sup", "name", "sketch", "placements", "drained",
                 "pressure", "health")

    def __init__(self, sup: EngineSupervisor):
        self.sup = sup
        self.name = sup.name
        self.sketch: OrderedDict = OrderedDict()  # digest -> None, LRU
        self.placements = 0
        self.drained = False     # last refresh verdict
        self.pressure: dict = {}
        self.health: dict = {}


class Router:
    """N supervised replicas, one `submit()/submit_stream()` front door.

    Either pass `model` (+ config/overrides) and the router builds
    `num_replicas` EngineSupervisors named `{name}-r{i}`, or pass
    prebuilt `replicas=[EngineSupervisor, ...]`. The router owns its
    replicas either way: `shutdown()` shuts them down."""

    def __init__(self, model=None, config: Optional[GenerationConfig] = None,
                 num_replicas: Optional[int] = None, name: str = "router",
                 replicas: Optional[Sequence[EngineSupervisor]] = None,
                 affinity: Optional[bool] = None,
                 sketch_digests: Optional[int] = None,
                 pressure_ttl_ms: Optional[float] = None,
                 metrics_port: Optional[int] = None, **overrides):
        self.name = name
        self._affinity = bool(flag("FLAGS_router_affinity")
                              if affinity is None else affinity)
        self._sketch_cap = int(flag("FLAGS_router_sketch_digests")
                               if sketch_digests is None else sketch_digests)
        self._ttl_ms = float(flag("FLAGS_router_pressure_ttl_ms")
                             if pressure_ttl_ms is None else pressure_ttl_ms)
        own_replicas = replicas is None
        if own_replicas:
            if model is None:
                raise InvalidArgumentError(
                    "Router needs either a model or prebuilt replicas")
            n = int(flag("FLAGS_router_replicas")
                    if num_replicas is None else num_replicas)
            if n < 1:
                raise InvalidArgumentError(
                    f"Router needs >= 1 replica, got {n}")
            built: List[EngineSupervisor] = []
            try:
                for i in range(n):
                    import copy
                    cfg = copy.copy(config) if config is not None else None
                    built.append(EngineSupervisor(
                        model, cfg, name=f"{name}-r{i}", **overrides))
            except Exception:
                for sup in built:
                    sup.shutdown(drain=False, timeout_s=5)
                raise
            replicas = built
        elif model is not None or config is not None or overrides:
            raise InvalidArgumentError(
                "pass either prebuilt replicas or model/config/overrides, "
                "not both")
        if not replicas:
            raise InvalidArgumentError("Router needs >= 1 replica")
        names = [sup.name for sup in replicas]
        if len(set(names)) != len(names):
            raise InvalidArgumentError(
                f"replica names must be unique, got {names}")
        self._replicas = [_Replica(sup) for sup in replicas]
        # affinity hashing + pressure-bucket arithmetic use replica 0's
        # shape config; heterogeneous page sizes would silently break
        # digest sharing with the engines' cache indexes, so refuse
        page_sizes = {sup._cfg.page_size for sup in replicas}
        if len(page_sizes) != 1:
            raise InvalidArgumentError(
                f"replicas disagree on page_size: {sorted(page_sizes)} — "
                "chain digests would not be comparable across the fleet")
        self._page_size = page_sizes.pop()
        self._default_max_new = replicas[0]._cfg.max_new_tokens
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._snap_t_ms = -1e18   # force first refresh
        self._timeline: deque = deque(maxlen=512)
        self._closed = False
        self._audit = audit.AuditLog(name)
        exporter.register_engine(self)
        self._owns_metrics_server = (metrics_port is not None
                                     and int(metrics_port) == 0)
        self.metrics_server = None
        try:
            self.metrics_server = exporter.start_metrics_server(
                metrics_port)
        except Exception:
            self.shutdown(drain=False, timeout_s=5)
            raise

    # -- placement ----------------------------------------------------------

    def _refresh_locked(self, force: bool = False) -> None:
        """Re-poll every replica's pressure + health when the cached
        snapshot is older than FLAGS_router_pressure_ttl_ms. Drain
        transitions (either direction) are audited ROUTE_DRAIN once per
        edge, not per placement."""
        now_ms = time.perf_counter() * 1000.0
        if not force and (now_ms - self._snap_t_ms) < self._ttl_ms:
            return
        self._snap_t_ms = now_ms
        monitor.stat_add("STAT_router_pressure_refreshes")
        tick: Dict[str, dict] = {}
        for rep in self._replicas:
            try:
                rep.pressure = rep.sup.pressure()
            except Exception as e:  # a dying replica reads as empty
                rep.pressure = {"error": repr(e)}
            try:
                rep.health = rep.sup.health()
            except Exception as e:  # a dying replica reads as drained
                rep.health = {"ready": False, "reason": repr(e)}
            was = rep.drained
            rep.drained = not rep.health.get("ready")
            if rep.drained != was:
                if rep.drained:
                    monitor.stat_add("STAT_router_drains")
                self._audit.audit(
                    "ROUTE_DRAIN", replica=rep.name,
                    drained=rep.drained,
                    verdict=rep.health.get("reason"),
                    breaker_open=bool(rep.health.get("breaker_open")))
            p = rep.pressure
            tick[rep.name] = {
                "ready": not rep.drained,
                "queue_depth": p.get("queue_depth", 0),
                "oldest_age_ms": p.get("oldest_age_ms", 0.0),
                "free_pages": p.get("free_pages", 0),
                "slots_free": p.get("slots_free", 0),
                "live": p.get("live", 0),
                # ISSUE 18: how much of the replica's prefix traffic the
                # host tier is absorbing — replicas without a tier read 0
                "tier_hit_rate": (p.get("tier") or {}).get("hit_rate",
                                                           0.0),
            }
        self._timeline.append({"t_ms": round(now_ms, 1),
                               "replicas": tick})

    @staticmethod
    def _headroom_at(pressure: dict, total_tokens: int) -> int:
        """Admittable-request count at the smallest snapshot shape
        covering this request's worst-case total; falls back to the
        tightest shape when nothing covers it."""
        head = pressure.get("headroom") or {}
        shapes = sorted((int(t), int(n)) for t, n in head.items())
        for t, n in shapes:
            if t >= total_tokens:
                return n
        return shapes[-1][1] if shapes else 0

    def _least_pressure_locked(self, cands: List[_Replica],
                               total_tokens: int) -> _Replica:
        offset = next(self._rr)

        def key(j: int):
            p = cands[j].pressure
            return (-self._headroom_at(p, total_tokens),
                    p.get("queue_depth", 0),
                    p.get("oldest_age_ms", 0.0),
                    (j - offset) % len(cands))  # rotate exact ties

        return cands[min(range(len(cands)), key=key)]

    def _pick_locked(self, digests: List[bytes], total_tokens: int,
                     exclude: set, trace: dict) -> Optional[_Replica]:
        cands = [r for r in self._replicas
                 if r.name not in exclude and not r.drained]
        if not cands:
            return None
        if self._affinity and digests:
            matched = []
            for r in cands:
                depth = 0
                for i in range(len(digests) - 1, -1, -1):
                    if digests[i] in r.sketch:
                        depth = i + 1
                        break
                matched.append(depth)
            best = max(matched)
            if best > 0:
                top = [r for r, m in zip(cands, matched) if m == best]
                rep = (top[0] if len(top) == 1
                       else self._least_pressure_locked(top, total_tokens))
                monitor.stat_add("STAT_router_affinity_hits")
                monitor.stat_add("STAT_router_affinity_pages", best)
                self._audit.audit(
                    "ROUTE_AFFINITY", replica=rep.name,
                    matched_pages=best, chain_pages=len(digests),
                    **trace)
                return rep
        if self._affinity:
            rep = self._least_pressure_locked(cands, total_tokens)
            policy = "least_pressure"
        else:
            rep = cands[next(self._rr) % len(cands)]
            policy = "round_robin"
        monitor.stat_add("STAT_router_least_pressure")
        self._audit.audit("ROUTE_LEAST_PRESSURE", replica=rep.name,
                          policy=policy,
                          queue_depth=rep.pressure.get("queue_depth", 0),
                          **trace)
        return rep

    def _note_placed_locked(self, rep: _Replica,
                            digests: List[bytes]) -> None:
        rep.placements += 1
        sk = rep.sketch
        for d in digests:
            if d in sk:
                sk.move_to_end(d)
            else:
                sk[d] = None
        while len(sk) > self._sketch_cap:
            sk.popitem(last=False)

    def _place(self, method: str, prompt_ids, kw: dict):
        """Pick a replica, call `method` on its supervisor, learn the
        placement. Placement-time typed failures (breaker open,
        shutdown, queue-full backpressure) re-route to the next-best
        replica — nothing was delivered yet, so exactly-once holds;
        anything the replica raises AFTER accepting the request
        propagates on the future/stream under its own supervisor's
        replay + retry-budget semantics."""
        if self._closed:
            raise UnavailableError(f"{self.name}: router shut down")
        monitor.stat_add("STAT_router_requests")
        # fleet trace context (ISSUE 20): the router is the request's
        # FIRST hop, so it mints the trace id and opens the fleet flow
        # chain — the id rides the placement audits (`trace=`), the
        # supervisor delegation, and every downstream incarnation's
        # span, so the merged fleet timeline links this decision to the
        # replica's prefill/decode and any post-restart replay
        tid = None
        if "trace_id" not in kw and trace_context.enabled():
            kw["trace_id"] = tid = trace_context.new_trace_id()
            tracer.flow("fleet_request", "s", trace_context.flow_id(tid))
        trace = {"trace": tid} if tid else {}
        digests = (chain_digests(prompt_ids, self._page_size)
                   if self._affinity else [])
        max_new = int(kw.get("max_new_tokens") or self._default_max_new)
        total = int(np.asarray(prompt_ids).size) + max_new
        tried: set = set()
        last_err: Optional[BaseException] = None
        for _ in range(len(self._replicas)):
            with self._lock:
                self._refresh_locked()
                rep = self._pick_locked(digests, total, tried, trace)
            if rep is None:
                break
            try:
                out = getattr(rep.sup, method)(prompt_ids, **kw)
            except (UnavailableError, ResourceExhaustedError) as e:
                # EngineOverloaded is the ResourceExhausted arm worth
                # rerouting (another replica has queue room); a
                # pool-can-never-fit ResourceExhausted repeats on every
                # identical replica but costs only one cheap re-raise
                # per survivor before the typed failure propagates
                last_err = e
                tried.add(rep.name)
                monitor.stat_add("STAT_router_reroutes")
                self._audit.audit("ROUTE_REROUTE", replica=rep.name,
                                  error=type(e).__name__, **trace)
                continue
            with self._lock:
                self._note_placed_locked(rep, digests)
            self._audit.flush_sink()
            return out
        self._audit.flush_sink()
        if last_err is not None:
            raise last_err
        raise UnavailableError(
            f"{self.name}: no replica placeable (all drained: SLO "
            "burn / breaker / not-ready)")

    # -- the engine surface -------------------------------------------------

    def submit(self, prompt_ids, **kw):
        """Same contract as GenerationEngine.submit, fleet-wide."""
        return self._place("submit", prompt_ids, kw)

    def submit_stream(self, prompt_ids, **kw) -> TokenStream:
        """Same contract as GenerationEngine.submit_stream; the stream
        is wired straight to the placed replica, so replay exactly-once
        semantics are the replica supervisor's own."""
        return self._place("submit_stream", prompt_ids, kw)

    def generate(self, prompt_ids, **kw) -> np.ndarray:
        return self._place("generate", prompt_ids, kw)

    # -- observability ------------------------------------------------------

    def pressure_timeline(self) -> List[dict]:
        with self._lock:
            return list(self._timeline)

    def stats(self) -> dict:
        """Router-level snapshot for `/stats`. Per-replica ENGINE stats
        stay under each supervisor's own exporter registration — this
        payload carries what only the router knows: placements,
        sketches, drain verdicts, the pressure timeline, and the
        placement audit tail."""
        with self._lock:
            reps = {
                rep.name: {
                    "placements": rep.placements,
                    "sketch_digests": len(rep.sketch),
                    "drained": rep.drained,
                    "pressure": dict(rep.pressure),
                    "supervisor": rep.sup.supervisor_stats(),
                } for rep in self._replicas}
            timeline = list(self._timeline)
        return {
            "router": {
                "affinity": self._affinity,
                "page_size": self._page_size,
                "sketch_capacity": self._sketch_cap,
                "pressure_ttl_ms": self._ttl_ms,
                "replicas": reps,
                "placements_total": sum(r["placements"]
                                        for r in reps.values()),
                "pressure_timeline": timeline,
                "audit_tail": self._audit.tail(256),
            }
        }

    def health(self) -> dict:
        """`/readyz` verdict: ready while >= 1 replica is placeable.
        Per-replica detail rides along so an operator can tell WHICH
        replica is burning/restarting from the router's own page."""
        with self._lock:
            self._refresh_locked()
            detail = {rep.name: {"ready": not rep.drained,
                                 "reason": rep.health.get("reason"),
                                 "breaker_open": bool(
                                     rep.health.get("breaker_open"))}
                      for rep in self._replicas}
        placeable = sum(1 for d in detail.values() if d["ready"])
        reason = ("ok" if placeable else
                  "no replica placeable (all drained/unready)")
        if self._closed:
            reason = "router shut down"
        return {"ready": placeable > 0 and not self._closed,
                "reason": reason, "placeable": placeable,
                "replicas": detail}

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for rep in self._replicas:
            rep.sup.shutdown(drain=drain, timeout_s=timeout_s)
        exporter.unregister_engine(self)
        self._audit.close()
        if self._owns_metrics_server and self.metrics_server is not None:
            self.metrics_server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
