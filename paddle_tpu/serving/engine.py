"""Dynamic micro-batching inference engine.

Design (the TPU serving hot loop, mirroring what PR 1 did for training):
submitters only validate + enqueue numpy; ONE worker thread owns all
device dispatch, coalescing queued requests into a batch, padding it up
to a pre-compiled bucket shape, and slicing results back per request.
Because `jit.save` now exports shape-polymorphic StableHLO (symbolic
batch dim), a single saved artifact serves every bucket and XLA compiles
exactly once per bucket — the compile count is observable through
`STAT_predictor_compiles` / `STAT_serving_bucket_compiles`.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

from ..framework import monitor
from ..framework.errors import (ExecutionTimeoutError, InvalidArgumentError,
                                UnavailableError)
from ..framework.flags import flag
from ..profiler import RecordEvent

__all__ = ["EngineConfig", "InferenceEngine"]


def _now_ms() -> float:
    return time.perf_counter() * 1000.0


class EngineConfig:
    """Micro-batcher knobs; every default comes from the FLAGS_serving_*
    registry so deployments tune engines without code changes."""

    def __init__(self, max_batch_size: Optional[int] = None,
                 max_batch_delay_ms: Optional[float] = None,
                 batch_buckets: Optional[Sequence[int]] = None,
                 max_queue_depth: Optional[int] = None,
                 request_timeout_ms: Optional[float] = None,
                 warmup: bool = True):
        self.max_batch_size = int(
            flag("FLAGS_serving_max_batch_size")
            if max_batch_size is None else max_batch_size)
        if self.max_batch_size < 1:
            raise InvalidArgumentError("max_batch_size must be >= 1")
        self.max_batch_delay_ms = float(
            flag("FLAGS_serving_max_batch_delay_ms")
            if max_batch_delay_ms is None else max_batch_delay_ms)
        explicit = batch_buckets is not None
        if batch_buckets is None:
            raw = str(flag("FLAGS_serving_batch_buckets"))
            batch_buckets = [int(x) for x in raw.split(",") if x.strip()]
        if explicit and any(int(b) < 1 or int(b) > self.max_batch_size
                            for b in batch_buckets):
            # flag-default buckets clip silently (a global default against
            # a local max), but an explicitly-passed bucket the engine
            # could never fill is a config error worth surfacing
            raise InvalidArgumentError(
                f"batch_buckets {tuple(batch_buckets)} contains buckets "
                f"outside [1, max_batch_size={self.max_batch_size}]")
        buckets = sorted({int(b) for b in batch_buckets
                          if 1 <= int(b) <= self.max_batch_size})
        if not buckets or buckets[-1] < self.max_batch_size:
            buckets.append(self.max_batch_size)  # every batch must fit
        self.batch_buckets = tuple(buckets)
        self.max_queue_depth = int(
            flag("FLAGS_serving_max_queue_depth")
            if max_queue_depth is None else max_queue_depth)
        self.request_timeout_ms = float(
            flag("FLAGS_serving_request_timeout_ms")
            if request_timeout_ms is None else request_timeout_ms)
        self.warmup = bool(warmup)


class _Request:
    __slots__ = ("arrays", "rows", "future", "deadline_ms", "t_enqueue_ms")

    def __init__(self, arrays, rows, future, deadline_ms, t_enqueue_ms):
        self.arrays = arrays
        self.rows = rows
        self.future = future
        self.deadline_ms = deadline_ms
        self.t_enqueue_ms = t_enqueue_ms


class InferenceEngine:
    """Thread-safe batched serving front-end over a saved artifact.

    `model` may be an artifact path prefix (as written by `jit.save` /
    `static.save_inference_model`), an `inference.Config`, an existing
    `inference.Predictor`, or any callable `fn(list_of_batched_arrays) ->
    outputs` (the test/bench seam). `submit()` returns a
    `concurrent.futures.Future` resolving to the per-request output list.

    Observability is process-global (the same contract as every other
    STAT counter): multiple engines share the STAT_serving_* counters,
    and the latency histogram is registered as "<name>_request_ms" — give
    each engine a unique `name` when per-engine latency attribution
    matters.

    Model contract (the requirement of every dynamic batcher, cf. TF
    Serving's batching): output row i must depend only on input row i.
    Inference-mode networks satisfy this; anything that mixes rows
    (train-mode batchnorm, cross-batch attention, pairwise x @ x.T
    outputs) must not be served through a batching engine. The engine
    detects the common violation class — outputs without a leading batch
    dim — and falls back to unpadded per-request execution, but
    row-mixing inside a batch-major output is semantically invisible and
    stays the caller's responsibility.
    """

    def __init__(self, model, config: Optional[EngineConfig] = None,
                 input_spec=None, name: str = "serving", **overrides):
        if config is None:
            config = EngineConfig(**overrides)
        elif overrides:
            raise InvalidArgumentError(
                "pass either an EngineConfig or keyword overrides, not both")
        import copy
        self._cfg = copy.copy(config)  # never mutate a shared caller config
        self.name = name
        self._build_runner(model, input_spec)
        # a fixed-batch artifact (pre-polymorphism save) admits exactly one
        # device shape: collapse bucketing to it rather than failing later
        fixed = self._fixed_batch()
        if fixed is not None:
            self._cfg.max_batch_size = fixed
            self._cfg.batch_buckets = (fixed,)
        self._queue = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._bucket_stats = {b: {"compiles": 0, "batches": 0, "rows": 0}
                              for b in self._cfg.batch_buckets}
        self._hist = monitor.histogram(f"{name}_request_ms")
        if self._cfg.warmup:
            self._warmup()
        self._worker = threading.Thread(target=self._worker_loop,
                                        name=f"{name}-batcher", daemon=True)
        self._worker.start()

    # -- model plumbing ----------------------------------------------------

    def _build_runner(self, model, input_spec):
        from .. import inference
        predictor = None
        if isinstance(model, str):
            predictor = inference.create_predictor(inference.Config(model))
        elif isinstance(model, inference.Config):
            predictor = inference.create_predictor(model)
        elif isinstance(model, inference.Predictor):
            predictor = model
        elif callable(model):
            predictor = None
        else:
            raise InvalidArgumentError(
                f"InferenceEngine: model must be a path, inference.Config, "
                f"Predictor, or callable, got {type(model).__name__}")
        self._predictor = predictor
        if predictor is not None:
            self._signature = predictor.input_signature()
            self._runner = predictor.run_device
        else:
            self._signature = self._spec_signature(input_spec)
            self._runner = model
        from ..inference import format_input_sig
        self._expect = (", ".join(format_input_sig(*s)
                                  for s in self._signature)
                        if self._signature else "")
        # set once a multi-request batch proves the model's outputs can't
        # be sliced per request; later batches then skip the wasted
        # batched execution and go straight to per-request dispatch
        self._unsliceable = False

    @staticmethod
    def _spec_signature(input_spec):
        """Optional signature for callable-backed engines: a list of
        InputSpec or (shape, dtype) pairs; None disables deep validation
        (and warmup, which needs concrete trailing dims)."""
        if input_spec is None:
            return None
        sig = []
        for i, spec in enumerate(input_spec):
            shape = getattr(spec, "shape", None)
            dtype = getattr(spec, "dtype", None)
            if shape is None:
                shape, dtype = spec
            dims = tuple(None if (d is None or d == -1) else int(d)
                         for d in shape)
            sig.append((getattr(spec, "name", None) or f"input_{i}",
                        dims, np.dtype(dtype) if dtype is not None
                        else np.dtype("float32")))
        return sig

    def _fixed_batch(self) -> Optional[int]:
        if not self._signature:
            return None
        dims0 = [d for _, dims, _ in self._signature if dims
                 for d in [dims[0]]]
        fixed = [d for d in dims0 if d is not None]
        return fixed[0] if fixed else None

    # -- request intake ----------------------------------------------------

    def _validate(self, inputs) -> tuple:
        from ..inference import check_fed_input
        sig = self._signature
        nin = len(sig) if sig else None
        if isinstance(inputs, np.ndarray) or not isinstance(
                inputs, (list, tuple)):
            inputs = [inputs]
        arrays = [np.asarray(a) for a in inputs]
        if nin is not None:
            expect = self._expect
            if len(arrays) != nin:
                raise InvalidArgumentError(
                    f"{self.name}: model expects {nin} input(s) "
                    f"[{expect}] but {len(arrays)} were submitted")
            try:
                # shared checker (same one Predictor.run uses), with the
                # batch axis exempt — the engine owns that dimension
                arrays = [check_fed_input(arr, n, dims, dtype,
                                          skip_batch_dim=True,
                                          ctx=self.name, expect=expect)
                          for arr, (n, dims, dtype) in zip(arrays, sig)]
            except ValueError as e:
                raise InvalidArgumentError(str(e)) from None
        rows = {int(a.shape[0]) for a in arrays if a.ndim >= 1}
        if len(rows) != 1:
            raise InvalidArgumentError(
                f"{self.name}: all inputs must share the leading batch "
                f"dim, got {[tuple(a.shape) for a in arrays]}")
        n = rows.pop()
        if n < 1:
            raise InvalidArgumentError(f"{self.name}: empty request")
        if n > self._cfg.max_batch_size:
            raise InvalidArgumentError(
                f"{self.name}: request batch {n} exceeds max_batch_size "
                f"{self._cfg.max_batch_size}; split the request")
        return arrays, n

    def submit(self, inputs, timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one request (arrays with a leading batch dim); returns a
        Future of the per-request output list. Raises `EngineOverloaded`
        when the queue is at max_queue_depth."""
        from . import EngineOverloaded
        with RecordEvent("serving::submit"):
            arrays, rows = self._validate(inputs)
            t = _now_ms()
            tmo = (self._cfg.request_timeout_ms if timeout_ms is None
                   else float(timeout_ms))
            # 0/None disables the deadline; a negative budget (caller's
            # remaining time already spent) expires immediately at pop
            req = _Request(arrays, rows, Future(),
                           None if not tmo else t + tmo, t)
            with self._cv:
                if self._closed:
                    raise UnavailableError(
                        f"{self.name}: engine is shut down")
                if len(self._queue) >= self._cfg.max_queue_depth:
                    monitor.stat_add("STAT_serving_rejected")
                    raise EngineOverloaded(
                        f"{self.name}: queue depth "
                        f"{self._cfg.max_queue_depth} reached "
                        f"({len(self._queue)} pending); shed load or "
                        f"raise FLAGS_serving_max_queue_depth")
                self._queue.append(req)
                monitor.stat_add("STAT_serving_queue_depth")
                self._cv.notify()
            monitor.stat_add("STAT_serving_requests")
            return req.future

    def run(self, inputs, timeout_ms: Optional[float] = None) -> List:
        """Synchronous submit: blocks for this request's result."""
        return self.submit(inputs, timeout_ms=timeout_ms).result()

    # -- worker ------------------------------------------------------------

    def _peek_live(self) -> Optional[_Request]:
        """Drop expired/cancelled requests from the queue head and return
        the first live one WITHOUT popping it (so the caller can size-check
        before claiming). Caller holds the lock."""
        while self._queue:
            req = self._queue[0]
            if req.deadline_ms is not None and _now_ms() > req.deadline_ms:
                self._queue.popleft()
                monitor.stat_sub("STAT_serving_queue_depth")
                monitor.stat_add("STAT_serving_timeouts")
                try:
                    req.future.set_exception(ExecutionTimeoutError(
                        f"{self.name}: request expired after "
                        f"{_now_ms() - req.t_enqueue_ms:.1f}ms in queue"))
                except Exception:  # racing caller-side cancel
                    pass
                continue
            if req.future.cancelled():
                self._queue.popleft()
                monitor.stat_sub("STAT_serving_queue_depth")
                continue
            return req
        return None

    def _take(self) -> Optional[_Request]:
        """Pop + claim the queue head; None if a racing cancel won.
        Caller holds the lock and has peeked the head."""
        req = self._queue.popleft()
        monitor.stat_sub("STAT_serving_queue_depth")
        if not req.future.set_running_or_notify_cancel():
            return None
        return req

    def _collect(self) -> Optional[List[_Request]]:
        """Block for the next batch: first live request opens the window,
        co-riders join until max_batch_size or max_batch_delay_ms. A
        request that would overflow the batch stays queued (peek before
        take), so rows never exceed the largest bucket."""
        cfg = self._cfg
        with self._cv:
            while not self._queue and not self._closed:
                self._cv.wait()
            if not self._queue and self._closed:
                return None
            first = None
            while first is None:
                if self._peek_live() is None:
                    return []  # nothing live; outer loop re-waits
                first = self._take()
            batch = [first]
            rows = first.rows
            window_end = _now_ms() + cfg.max_batch_delay_ms
            while rows < cfg.max_batch_size:
                head = self._peek_live() if self._queue else None
                if head is not None:
                    if rows + head.rows > cfg.max_batch_size:
                        break
                    got = self._take()
                    if got is None:
                        continue
                    batch.append(got)
                    rows += got.rows
                else:
                    remain = window_end - _now_ms()
                    if remain <= 0 or self._closed:
                        break
                    self._cv.wait(remain / 1000.0)
                    if not self._queue and _now_ms() >= window_end:
                        break
            return batch

    def _worker_loop(self):
        batch = None
        try:
            while True:
                batch = self._collect()
                if batch is None:
                    return
                if batch:
                    self._dispatch(batch)
                batch = None
        except BaseException as e:  # noqa: BLE001 — never hang submitters
            # fail BOTH the already-claimed in-flight batch and everything
            # still queued, or their submitters block on result() forever
            stranded = list(batch or [])
            with self._cv:
                self._closed = True
                while self._queue:
                    stranded.append(self._queue.popleft())
                    monitor.stat_sub("STAT_serving_queue_depth")
            for req in stranded:
                try:
                    req.future.set_exception(UnavailableError(
                        f"{self.name}: worker died: {e!r}"))
                except Exception:
                    pass
            raise

    # -- execution ---------------------------------------------------------

    def _bucket_for(self, rows: int) -> int:
        for b in self._cfg.batch_buckets:
            if b >= rows:
                return b
        return self._cfg.batch_buckets[-1]

    def _execute(self, arrays, rows: int, bucket: int) -> List[np.ndarray]:
        """Pad to the bucket, run the model once, host-sync once."""
        if rows < bucket:
            arrays = [np.concatenate(
                [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)])
                for a in arrays]
        c0 = (self._predictor.compile_count
              if self._predictor is not None else None)
        with RecordEvent(f"serving::batch[b={bucket}]"):
            out = self._runner(list(arrays))
        # setdefault: unsliceable models run ad-hoc exact-size "buckets"
        st = self._bucket_stats.setdefault(
            bucket, {"compiles": 0, "batches": 0, "rows": 0})
        if c0 is not None:
            # exact: the predictor counts jit traces; this engine's single
            # worker (plus init-time warmup) is the only dispatcher
            d = self._predictor.compile_count - c0
        else:
            # callable-backed runner: no trace counter, mark first dispatch
            d = 1 if st["compiles"] == 0 else 0
        if d:
            st["compiles"] += d
            monitor.stat_add("STAT_serving_bucket_compiles", d)
        import jax
        leaves = jax.tree_util.tree_leaves(out)
        return [np.asarray(leaf) for leaf in leaves]

    def _dispatch(self, batch: List[_Request]):
        if self._unsliceable and len(batch) > 1:
            for req in batch:
                self._dispatch([req])
            return
        rows = sum(r.rows for r in batch)
        # an unsliceable model's outputs may aggregate over batch rows, so
        # zero padding would contaminate them — run exact-size (one
        # compile per observed size is the price of such models)
        bucket = rows if self._unsliceable else self._bucket_for(rows)
        nin = len(batch[0].arrays)
        try:
            # concat inside the try: on a spec-less engine, requests with
            # inconsistent trailing dims must poison only themselves, not
            # kill the worker
            concat = [batch[0].arrays[i] if len(batch) == 1 else
                      np.concatenate([r.arrays[i] for r in batch])
                      for i in range(nin)]
            outs = self._execute(concat, rows, bucket)
        except Exception as e:  # noqa: BLE001
            if len(batch) == 1:
                monitor.stat_add("STAT_serving_request_errors")
                try:
                    batch[0].future.set_exception(e)
                except Exception:
                    pass
                return
            # poisoned batch: isolate — each request reruns alone so the
            # error lands only on the offending future and the engine
            # keeps serving everyone else
            monitor.stat_add("STAT_serving_batch_retries")
            for req in batch:
                self._dispatch([req])
            return
        if (not self._unsliceable
                and (len(batch) > 1 or rows < bucket)
                and any(getattr(o, "ndim", 0) < 1 or o.shape[0] != bucket
                        for o in outs)):
            # an output without the batch dim leading can't be sliced back
            # per request, and if the batch was padded it may even be
            # computed over the padding rows — never deliver co-mingled or
            # padding-contaminated data; rerun each request alone and
            # UNPADDED (the _unsliceable verdict makes the recursive calls
            # use bucket == rows), and remember the verdict so future
            # batches skip the wasted bucketed execution
            self._unsliceable = True
            monitor.stat_add("STAT_serving_unsliceable_batches")
            for req in batch:
                self._dispatch([req])
            return
        st = self._bucket_stats[bucket]
        st["batches"] += 1
        st["rows"] += rows
        monitor.stat_add("STAT_serving_batches")
        monitor.stat_add("STAT_serving_batch_rows", rows)
        monitor.stat_add("STAT_serving_batch_slots", bucket)
        t_done = _now_ms()
        off = 0
        for req in batch:
            # multi-request batches are guaranteed batch-major by the guard
            # above; for a lone request, a non-batch-major output (e.g. a
            # per-batch aggregate) is its own result and passes through whole
            res = [o[off:off + req.rows]
                   if (getattr(o, "ndim", 0) >= 1 and o.shape[0] == bucket)
                   else o for o in outs]
            off += req.rows
            self._hist.observe(t_done - req.t_enqueue_ms)
            try:
                req.future.set_result(res)
            except Exception:  # racing caller-side cancel
                pass

    def _warmup(self):
        """Compile every bucket up front so no live request pays a compile.
        Needs concrete trailing dims; silently skipped otherwise."""
        if not self._signature:
            return
        shapes = []
        for _, dims, dtype in self._signature:
            if dims is None or any(d is None for d in dims[1:]):
                return
            shapes.append((tuple(dims[1:]), dtype or np.dtype("float32")))
        with RecordEvent("serving::warmup"):
            for b in self._cfg.batch_buckets:
                arrays = [np.zeros((b,) + rest, dtype)
                          for rest, dtype in shapes]
                self._execute(arrays, b, b)

    # -- lifecycle / introspection -----------------------------------------

    def stats(self) -> dict:
        """Engine-local snapshot: per-bucket compile/batch/occupancy, live
        queue depth, and the latency histogram percentiles."""
        with self._cv:
            depth = len(self._queue)
        slots = sum(b * s["batches"]
                    for b, s in self._bucket_stats.items())
        served = sum(s["rows"] for s in self._bucket_stats.values())
        return {
            "buckets": {b: dict(s) for b, s in self._bucket_stats.items()},
            "queue_depth": depth,
            "rows_served": served,
            "mean_occupancy": round(served / slots, 4) if slots else 0.0,
            "latency_ms": self._hist.snapshot(),
        }

    def shutdown(self, drain: bool = True, timeout_s: Optional[float] = None):
        """Stop intake; by default the worker drains every queued request
        before exiting. With drain=False pending futures fail fast."""
        with self._cv:
            self._closed = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    monitor.stat_sub("STAT_serving_queue_depth")
                    try:
                        req.future.set_exception(UnavailableError(
                            f"{self.name}: engine shut down"))
                    except Exception:
                        pass
            self._cv.notify_all()
        self._worker.join(timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
