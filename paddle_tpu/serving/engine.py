"""Pipelined multi-device micro-batching inference engine.

Design (the TPU serving hot loop, Orca-style iteration overlap):
submitters only validate + enqueue numpy; ONE shared **collector**
thread owns batching — it coalesces queued requests into a batch, then
routes it to one of N per-device **dispatch lanes** (round-robin with a
least-inflight tiebreak). Each lane is a Predictor replica pinned to one
local device plus two threads: a *dispatcher* that pads the batch up to
a pre-compiled bucket shape and enqueues the device call (JAX async
dispatch — no host sync), and a *completer* that blocks on the results,
slices them back per request, and resolves futures. Because dispatch and
completion are decoupled, lane K admits batch N+1 while batch N is still
computing, and with multiple lanes every local chip serves traffic
concurrently. In-flight batches per lane are bounded by
`FLAGS_serving_max_inflight`, so backpressure still reaches
`EngineOverloaded` at the front door instead of piling work on the
device queue. `jit.save` exports shape-polymorphic StableHLO (symbolic
batch dim), so a single saved artifact serves every (device, bucket)
pair and XLA compiles exactly once per pair — observable through the
per-replica `Predictor.compile_count` / `STAT_predictor_compiles`.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

from ..framework import monitor
from ..framework.errors import (ExecutionTimeoutError, InvalidArgumentError,
                                UnavailableError)
from ..framework.flags import flag
from ..profiler import (RecordEvent, device_telemetry, exporter,
                        flight_recorder, spans)
from .restart import RestartBackoff

__all__ = ["EngineConfig", "InferenceEngine"]

# intake depth moves both ways: Prometheus gauge, but its stat_add/
# stat_sub deltas still relay across processes (monitor is the single
# registry of gauge names — ISSUE 11)
monitor.register_gauge("STAT_serving_queue_depth", updown=True)


def _now_ms() -> float:
    return time.perf_counter() * 1000.0


class EngineConfig:
    """Micro-batcher knobs; every default comes from the FLAGS_serving_*
    registry so deployments tune engines without code changes."""

    def __init__(self, max_batch_size: Optional[int] = None,
                 max_batch_delay_ms: Optional[float] = None,
                 batch_buckets: Optional[Sequence[int]] = None,
                 max_queue_depth: Optional[int] = None,
                 request_timeout_ms: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 warmup: bool = True):
        self.max_batch_size = int(
            flag("FLAGS_serving_max_batch_size")
            if max_batch_size is None else max_batch_size)
        if self.max_batch_size < 1:
            raise InvalidArgumentError("max_batch_size must be >= 1")
        self.max_batch_delay_ms = float(
            flag("FLAGS_serving_max_batch_delay_ms")
            if max_batch_delay_ms is None else max_batch_delay_ms)
        explicit = batch_buckets is not None
        if batch_buckets is None:
            raw = str(flag("FLAGS_serving_batch_buckets"))
            batch_buckets = [int(x) for x in raw.split(",") if x.strip()]
        if explicit and any(int(b) < 1 or int(b) > self.max_batch_size
                            for b in batch_buckets):
            # flag-default buckets clip silently (a global default against
            # a local max), but an explicitly-passed bucket the engine
            # could never fill is a config error worth surfacing
            raise InvalidArgumentError(
                f"batch_buckets {tuple(batch_buckets)} contains buckets "
                f"outside [1, max_batch_size={self.max_batch_size}]")
        buckets = sorted({int(b) for b in batch_buckets
                          if 1 <= int(b) <= self.max_batch_size})
        if not buckets or buckets[-1] < self.max_batch_size:
            buckets.append(self.max_batch_size)  # every batch must fit
        self.batch_buckets = tuple(buckets)
        self.max_queue_depth = int(
            flag("FLAGS_serving_max_queue_depth")
            if max_queue_depth is None else max_queue_depth)
        self.request_timeout_ms = float(
            flag("FLAGS_serving_request_timeout_ms")
            if request_timeout_ms is None else request_timeout_ms)
        self.max_inflight = int(
            flag("FLAGS_serving_max_inflight")
            if max_inflight is None else max_inflight)
        if self.max_inflight < 1:
            raise InvalidArgumentError("max_inflight must be >= 1")
        self.warmup = bool(warmup)


class _Request:
    __slots__ = ("arrays", "rows", "future", "deadline_ms", "t_enqueue_ms",
                 "span")

    def __init__(self, arrays, rows, future, deadline_ms, t_enqueue_ms,
                 span=None):
        self.arrays = arrays
        self.rows = rows
        self.future = future
        self.deadline_ms = deadline_ms
        self.t_enqueue_ms = t_enqueue_ms
        self.span = span  # per-request phase clock (None when spans off)


class _Lane:
    """One per-device dispatch lane: a Predictor replica (or callable)
    plus a dispatcher thread (pads + enqueues the device call, no host
    sync) and a completer thread (blocks on results, slices, resolves
    futures). A lane that dies — a BaseException escaping either thread —
    fails only its OWN in-flight work and is taken out of rotation; the
    other lanes keep serving.
    """

    def __init__(self, engine: "InferenceEngine", index: int, runner,
                 predictor, device):
        self.engine = engine
        self.index = index
        self.runner = runner
        self.predictor = predictor
        self.device = device
        self.alive = True
        self.death_cause: Optional[BaseException] = None
        self.restarts = 0           # times this lane slot was rebuilt
        self.will_restart = False   # restart RESERVED in _die's locked
        #                             section, so the collector never
        #                             sees all-dead with a rebuild
        #                             still unannounced
        self.quiet_death = False    # previous death was > a quiet
        #                             window ago: budget+backoff reset
        self.inflight = 0           # routed batches not yet resolved (engine._cv)
        self.batches = 0            # completed device batches (engine._stats_lock)
        self.rows = 0
        self.bucket_compiles = {}   # bucket -> compiles on THIS replica
        self.inbox: "queue.Queue" = queue.Queue()    # collector -> dispatcher
        self.pending: "queue.Queue" = queue.Queue()  # dispatcher -> completer
        # serializes runner calls + compile accounting: the completer's
        # poison/unsliceable reruns share this replica with the
        # dispatcher, and overlapping compile_count windows would
        # double-count a trace (it also keeps a single-lane callable
        # single-threaded, as the engine docstring promises). Held only
        # across dispatch — never the host sync — so pipelining survives.
        self._run_lock = threading.Lock()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"{engine.name}-lane{index}-dispatch")
        self._completer = threading.Thread(
            target=self._complete_loop, daemon=True,
            name=f"{engine.name}-lane{index}-complete")

    def start(self):
        self._dispatcher.start()
        self._completer.start()

    def join(self, deadline):
        """deadline: time.monotonic() instant (None = wait forever)."""
        for t in (self._dispatcher, self._completer):
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))

    # -- execution ---------------------------------------------------------

    def _execute_async(self, arrays, rows: int, bucket: int, reqs=None):
        """Pad to the bucket and enqueue the device call; returns
        device-resident output leaves WITHOUT a host sync (the completer
        blocks on them). Compile accounting is exact per replica: jit
        traces are synchronous even under async dispatch. `reqs` (live
        requests riding this dispatch, None during warmup) get their
        span phase stamps and flow-step events here."""
        if rows < bucket:
            arrays = [np.concatenate(
                [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)])
                for a in arrays]
        if reqs:
            t_pad = time.perf_counter()
            for r in reqs:
                if r.span is not None:
                    r.span.lane = self.index
                    r.span.bucket = bucket
                    r.span.stamp("padded", t_pad)
        with self._run_lock:
            c0 = (self.predictor.compile_count
                  if self.predictor is not None else None)
            t_run0 = time.perf_counter()
            with RecordEvent(
                    f"serving::lane{self.index}::dispatch[b={bucket}]"):
                if self.device is not None and self.predictor is None:
                    # jax-backed callable lanes honor the lane device too;
                    # predictor replicas pin themselves (Predictor._device)
                    import jax
                    with jax.default_device(self.device):
                        out = self.runner(list(arrays))
                else:
                    out = self.runner(list(arrays))
                if reqs:
                    # flow steps INSIDE the dispatch scope so the arrows
                    # attach to this lane's dispatch slice
                    for r in reqs:
                        if r.span is not None:
                            r.span.flow("t")
            t_run1 = time.perf_counter()
            import jax
            leaves = jax.tree_util.tree_leaves(out)
            d = (self.predictor.compile_count - c0
                 if c0 is not None else None)
        if reqs:
            for r in reqs:
                if r.span is not None:
                    r.span.stamp("dispatched", t_run1)
        eng = self.engine
        with eng._stats_lock:
            # setdefault: unsliceable models run ad-hoc exact-size "buckets"
            st = eng._bucket_stats.setdefault(
                bucket, {"compiles": 0, "batches": 0, "rows": 0})
            lane_c = self.bucket_compiles.setdefault(bucket, 0)
            if d is None:
                # callable-backed runner: no trace counter, mark the first
                # dispatch of each (lane, bucket); predictor lanes got the
                # exact per-replica trace delta under the run lock above
                d = 1 if lane_c == 0 else 0
            if d:
                self.bucket_compiles[bucket] = lane_c + d
                st["compiles"] += d
        if d:
            monitor.stat_add("STAT_serving_bucket_compiles", d)
            # the dispatch wall of a compiling call is compile-dominated:
            # feed the cumulative per-(device, bucket) compile ledger
            dev_key = (getattr(self.device, "id", None)
                       if self.device is not None else None)
            device_telemetry.note_compile(
                f"d{dev_key}" if dev_key is not None else f"lane{self.index}",
                bucket, t_run1 - t_run0)
        return leaves

    def _units_for(self, batch: List[_Request]):
        """Dispatch a claimed batch; returns completion units
        (reqs, rows, bucket, leaves, err). A dispatch-time failure of a
        multi-request batch is retried per request so the error lands
        only on the offending future (poison isolation, per lane)."""
        eng = self.engine
        if eng._unsliceable and len(batch) > 1:
            return [u for req in batch for u in self._units_for([req])]
        rows = sum(r.rows for r in batch)
        # an unsliceable model's outputs may aggregate over batch rows, so
        # zero padding would contaminate them — run exact-size (one
        # compile per observed size is the price of such models)
        bucket = rows if eng._unsliceable else eng._bucket_for(rows)
        nin = len(batch[0].arrays)
        try:
            # concat inside the try: on a spec-less engine, requests with
            # inconsistent trailing dims must poison only themselves, not
            # kill the lane
            concat = [batch[0].arrays[i] if len(batch) == 1 else
                      np.concatenate([r.arrays[i] for r in batch])
                      for i in range(nin)]
            leaves = self._execute_async(concat, rows, bucket, reqs=batch)
            return [(batch, rows, bucket, leaves, None)]
        except Exception as e:  # noqa: BLE001
            if len(batch) == 1:
                return [(batch, rows, bucket, None, e)]
            monitor.stat_add("STAT_serving_batch_retries")
            flight_recorder.dump("serving_poisoned_batch", {
                "engine": eng.name, "lane": self.index, "stage": "dispatch",
                "bucket": bucket, "rows": rows, "requests": len(batch),
                "error": repr(e)})
            return [u for req in batch for u in self._units_for([req])]

    def warm(self, shapes):
        """Compile every bucket on THIS lane's device, blocking on each."""
        for b in self.engine._cfg.batch_buckets:
            arrays = [np.zeros((b,) + rest, dtype) for rest, dtype in shapes]
            for leaf in self._execute_async(arrays, b, b):
                np.asarray(leaf)

    # -- dispatcher --------------------------------------------------------

    def _dispatch_loop(self):
        batch = None
        try:
            while True:
                batch = self.inbox.get()
                if batch is None:
                    self.pending.put(None)
                    return
                if not self.alive:  # completer died while we were idle
                    self._fail_reqs(batch, self.death_cause)
                    self._dec_inflight(1)
                    batch = None
                    continue
                self.pending.put(self._units_for(batch))
                if not self.alive:
                    # completer died racing the put above: it may have
                    # drained `pending` already, so drain again ourselves
                    # — one side is guaranteed to see the entry
                    dropped = self._drain_pending()
                    if dropped:
                        self._dec_inflight(dropped)
                batch = None
        except BaseException as e:  # noqa: BLE001 — lane death, not engine death
            self._die(e, batch)
            self.pending.put(None)  # completer finishes dispatched work, exits
            raise

    # -- completer ---------------------------------------------------------

    def _expired(self, req: _Request, t_ms: float) -> bool:
        """Completion-time deadline: a request whose deadline lapsed while
        its batch was on-device gets ExecutionTimeoutError, not a late
        result the caller already gave up on."""
        if req.deadline_ms is None or t_ms <= req.deadline_ms:
            return False
        monitor.stat_add("STAT_serving_timeouts")
        try:
            req.future.set_exception(ExecutionTimeoutError(
                f"{self.engine.name}: request expired after "
                f"{t_ms - req.t_enqueue_ms:.1f}ms (deadline passed while "
                f"the batch was in flight)"))
        except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled, the timeout has nowhere to land
            pass
        return True

    def _complete_unit(self, reqs, rows, bucket, leaves, err):
        eng = self.engine
        outs = None
        if err is None:
            try:
                with RecordEvent(
                        f"serving::lane{self.index}::complete[b={bucket}]"):
                    # THE host sync: under async dispatch a device-side
                    # failure (nan trap, OOM) surfaces here, not at dispatch
                    outs = [np.asarray(leaf) for leaf in leaves]
                    t_sync = time.perf_counter()
                    for req in reqs:
                        if req.span is not None:
                            req.span.stamp("device_done", t_sync)
                            req.span.flow("f")  # arrow ends in this scope
            except Exception as e:  # noqa: BLE001
                err = e
        if err is not None:
            if len(reqs) == 1:
                monitor.stat_add("STAT_serving_request_errors")
                try:
                    reqs[0].future.set_exception(err)
                except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
                    pass
                return
            # poisoned batch: isolate — each request reruns alone so the
            # error lands only on the offending future and the lane
            # keeps serving everyone else
            monitor.stat_add("STAT_serving_batch_retries")
            flight_recorder.dump("serving_poisoned_batch", {
                "engine": eng.name, "lane": self.index,
                "stage": "complete", "bucket": bucket, "rows": rows,
                "requests": len(reqs), "error": repr(err)})
            for req in reqs:
                if not self._expired(req, _now_ms()):
                    for u in self._units_for([req]):
                        self._complete_unit(*u)
            return
        if (not eng._unsliceable
                and (len(reqs) > 1 or rows < bucket)
                and any(getattr(o, "ndim", 0) < 1 or o.shape[0] != bucket
                        for o in outs)):
            # an output without the batch dim leading can't be sliced back
            # per request, and if the batch was padded it may even be
            # computed over the padding rows — never deliver co-mingled or
            # padding-contaminated data; rerun each request alone and
            # UNPADDED (the _unsliceable verdict makes the reruns use
            # bucket == rows), and remember the verdict so future batches
            # skip the wasted bucketed execution
            eng._unsliceable = True
            monitor.stat_add("STAT_serving_unsliceable_batches")
            for req in reqs:
                if not self._expired(req, _now_ms()):
                    for u in self._units_for([req]):
                        self._complete_unit(*u)
            return
        with eng._stats_lock:
            st = eng._bucket_stats[bucket]
            st["batches"] += 1
            st["rows"] += rows
            self.batches += 1
            self.rows += rows
        monitor.stat_add("STAT_serving_batches")
        monitor.stat_add("STAT_serving_batch_rows", rows)
        monitor.stat_add("STAT_serving_batch_slots", bucket)
        monitor.stat_add(f"STAT_serving_lane{self.index}_batches")
        monitor.stat_add(f"STAT_serving_lane{self.index}_rows", rows)
        t_done = _now_ms()
        off = 0
        for req in reqs:
            # multi-request batches are guaranteed batch-major by the guard
            # above; for a lone request, a non-batch-major output (e.g. a
            # per-batch aggregate) is its own result and passes through whole
            res = [o[off:off + req.rows]
                   if (getattr(o, "ndim", 0) >= 1 and o.shape[0] == bucket)
                   else o for o in outs]
            off += req.rows
            if req.span is not None:
                req.span.stamp("sliced")
            eng._hist.observe(t_done - req.t_enqueue_ms)
            if self._expired(req, t_done):
                continue  # abandoned span: phase hists mean DELIVERED work
            try:
                req.future.set_result(res)
            except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled, the result has nowhere to land
                pass
            else:
                if req.span is not None:
                    req.span.stamp("resolved")
                    req.span.finish()

    def _complete_loop(self):
        units = None
        try:
            while True:
                units = self.pending.get()
                if units is None:
                    return
                for u in units:
                    self._complete_unit(*u)
                units = None
                self._dec_inflight(1)
        except BaseException as e:  # noqa: BLE001
            self._die(e, None,
                      current_reqs=[r for u in (units or []) for r in u[0]])
            raise

    # -- death / accounting ------------------------------------------------

    def _dec_inflight(self, n: int):
        eng = self.engine
        with eng._cv:
            self.inflight -= n
            eng._cv.notify_all()  # collector may be waiting for capacity

    def _fail_reqs(self, reqs, exc):
        err = UnavailableError(
            f"{self.engine.name} lane{self.index} "
            f"(device={self.device}): died: {exc!r}")
        for req in reqs:
            try:
                req.future.set_exception(err)
            except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
                pass

    def _drain_pending(self, span_sink=None) -> int:
        """Fail every dispatched-but-uncompleted unit; returns how many
        routed batches were dropped (for in-flight accounting).
        `span_sink` collects the failed requests for the postmortem's
        in-flight span list."""
        dropped = 0
        while True:
            try:
                units = self.pending.get_nowait()
            except queue.Empty:
                return dropped
            if units is None:
                continue
            dropped += 1
            for u in units:
                self._fail_reqs(u[0], self.death_cause)
                if span_sink is not None:
                    span_sink.extend(u[0])

    def _die(self, exc: BaseException, current_batch,
             current_reqs: Optional[list] = None):
        """Take this lane out of rotation and fail ONLY its own in-flight
        work: the current batch/units, everything routed to its inbox,
        and (on completer death) everything awaiting completion."""
        eng = self.engine
        stranded_batches = []
        saw_sentinel = False
        with eng._cv:
            first = self.alive
            self.alive = False
            if self.death_cause is None:
                self.death_cause = exc
            if first:
                # reserve the restart UNDER the same lock that marks
                # this lane dead: a collector waking on the notify
                # below must never observe all-dead with zero pending
                # rebuilds and wrongly close the engine (ISSUE 15)
                limit = int(flag("FLAGS_serving_lane_restarts"))
                if (limit > 0 and not eng._closed
                        and eng._lanes[self.index] is self):
                    backoff = eng._lane_backoffs.setdefault(
                        self.index, RestartBackoff(
                            float(flag("FLAGS_gen_restart_backoff_ms"))))
                    # shared quiet-window policy (restart.py): a slot
                    # that survived a full quiet window earns its base
                    # backoff AND its restart budget back — the budget
                    # check must see that verdict, or a long-lived
                    # lane's occasional transients exhaust it forever
                    self.quiet_death = backoff.note_death(
                        float(flag("FLAGS_gen_breaker_window_s")))
                    used = 0 if self.quiet_death else self.restarts
                    if used < limit:
                        self.will_restart = True
                        eng._restarting += 1
            while True:  # puts happen under _cv, so this drain is consistent
                try:
                    item = self.inbox.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    saw_sentinel = True  # shutdown's drain sentinel
                else:
                    stranded_batches.append(item)
            if saw_sentinel:
                # restore it — a completer-side death must not strand the
                # dispatcher in inbox.get() forever and hang shutdown()
                self.inbox.put(None)
            eng._cv.notify_all()
        if first:
            monitor.stat_add("STAT_serving_lane_deaths")
            monitor.stat_add(f"STAT_serving_lane{self.index}_deaths")
        dropped = 0
        died_reqs = []  # everything this death failed, for span postmortem
        if current_batch is not None:
            self._fail_reqs(current_batch, exc)
            died_reqs.extend(current_batch)
            dropped += 1
        if current_reqs:
            self._fail_reqs(current_reqs, exc)
            died_reqs.extend(current_reqs)
            dropped += 1
        for b in stranded_batches:
            self._fail_reqs(b, exc)
            died_reqs.extend(b)
            dropped += 1
        if current_reqs is not None:
            # completer is the dying thread: nobody will consume `pending`
            dropped += self._drain_pending(span_sink=died_reqs)
        if dropped:
            self._dec_inflight(dropped)
        if first:
            # postmortem artifact AFTER every stranded future is failed:
            # the dump is file IO and must never delay a waiting caller.
            # Its event tail carries this lane's last dispatch/complete
            # scopes — the context the raised UnavailableError lacks —
            # and the in-flight spans say exactly which phase each
            # stranded request died in.
            flight_recorder.dump("serving_lane_death", {
                "engine": eng.name, "lane": self.index,
                "device": str(self.device) if self.device is not None
                else None, "thread": threading.current_thread().name,
                "error": repr(exc), "dropped_batches": dropped,
                "lane_batches_completed": self.batches,
                "lane_rows_completed": self.rows,
                "lane_restarts": self.restarts,
                "inflight_spans": [r.span.to_dict() for r in died_reqs
                                   if r.span is not None][:64]})
            # per-lane resurrection (ISSUE 15): with
            # FLAGS_serving_lane_restarts > 0, rebuild this lane slot
            # in place (fresh threads, same replica/device) so a
            # transient fault no longer permanently shrinks capacity —
            # runs on the dying thread, AFTER its own work is failed
            eng._maybe_restart_lane(self)


class InferenceEngine:
    """Thread-safe batched serving front-end over a saved artifact,
    pipelined across every local device.

    `model` may be an artifact path prefix (as written by `jit.save` /
    `static.save_inference_model`), an `inference.Config`, an existing
    `inference.Predictor`, any callable `fn(list_of_batched_arrays) ->
    outputs`, or a list of such callables (one dispatch lane each — the
    test/bench seam). `submit()` returns a `concurrent.futures.Future`
    resolving to the per-request output list.

    `devices` picks the dispatch lanes: None defaults from
    `FLAGS_serving_devices` — for a path/Config model the default is
    EVERY local device (one Predictor replica per chip); a user-built
    Predictor or callable stays single-lane unless `devices` says
    otherwise. Accepts 'all', an int count, or a list of local device
    indices / jax Devices. A callable model with multi-lane `devices`
    must be thread-safe — lanes dispatch concurrently.

    Observability is process-global (the same contract as every other
    STAT counter): multiple engines share the STAT_serving_* counters,
    and the latency/in-flight histograms are registered as
    "<name>_request_ms" / "<name>_inflight_depth" — give each engine a
    unique `name` when per-engine attribution matters.

    Model contract (the requirement of every dynamic batcher, cf. TF
    Serving's batching): output row i must depend only on input row i.
    Inference-mode networks satisfy this; anything that mixes rows
    (train-mode batchnorm, cross-batch attention, pairwise x @ x.T
    outputs) must not be served through a batching engine. The engine
    detects the common violation class — outputs without a leading batch
    dim — and falls back to unpadded per-request execution, but
    row-mixing inside a batch-major output is semantically invisible and
    stays the caller's responsibility.

    Numerics: results are bit-identical within one (device, bucket) —
    padding and co-riders never change a request's rows — but different
    buckets, and different lanes, are different compiled executables
    whose float reductions may be ordered differently. Callers that need
    bit-stable replies across repeats must pin a single device and
    bucket.
    """

    def __init__(self, model, config: Optional[EngineConfig] = None,
                 input_spec=None, name: str = "serving", devices=None,
                 metrics_port: Optional[int] = None, **overrides):
        if config is None:
            config = EngineConfig(**overrides)
        elif overrides:
            raise InvalidArgumentError(
                "pass either an EngineConfig or keyword overrides, not both")
        import copy
        self._cfg = copy.copy(config)  # never mutate a shared caller config
        self.name = name
        self._stats_lock = threading.Lock()
        self._queue = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._rr = 0
        # lane resurrection (ISSUE 15): lanes mid-rebuild count here so
        # the collector WAITS through an all-dead-but-restarting window
        # instead of declaring the engine dead; one backoff policy per
        # lane slot (a flapping lane escalates, its neighbors don't)
        self._restarting = 0
        self._lane_backoffs = {}
        # set once a multi-request batch proves the model's outputs can't
        # be sliced per request; later batches then skip the wasted
        # batched execution and go straight to per-request dispatch
        self._unsliceable = False
        self._build_lanes(model, input_spec, devices)
        # a fixed-batch artifact (pre-polymorphism save) admits exactly one
        # device shape: collapse bucketing to it rather than failing later
        fixed = self._fixed_batch()
        if fixed is not None:
            self._cfg.max_batch_size = fixed
            self._cfg.batch_buckets = (fixed,)
        self._bucket_stats = {b: {"compiles": 0, "batches": 0, "rows": 0}
                              for b in self._cfg.batch_buckets}
        self._hist = monitor.histogram(f"{name}_request_ms")
        self._inflight_hist = monitor.histogram(f"{name}_inflight_depth")
        # observability surfaces BEFORE warmup: registering early means
        # /readyz reports this engine as warming up (ready:false with
        # warmup_complete:false) instead of not existing — the signal a
        # router needs to hold traffic during a rolling restart
        self._warmed = False
        flight_recorder.touch()
        device_telemetry.touch()
        exporter.register_engine(self)
        if self._cfg.warmup:
            self._warmup()
        self._warmed = True
        for lane in self._lanes:
            lane.start()
        self._collector = threading.Thread(target=self._collector_loop,
                                           name=f"{name}-collector",
                                           daemon=True)
        self._collector.start()
        # an explicit port 0 binds an ephemeral, never-shared server —
        # this engine owns it and must close it on shutdown
        self._owns_metrics_server = (metrics_port is not None
                                     and int(metrics_port) == 0)
        self.metrics_server = None
        try:
            self.metrics_server = exporter.start_metrics_server(
                metrics_port)
        except Exception:
            # the lanes + collector are already running; a port-bind
            # failure must not leak them with no handle to stop them
            self.shutdown(drain=False, timeout_s=5)
            raise

    # -- model / lane plumbing ---------------------------------------------

    def _build_lanes(self, model, input_spec, devices):
        from .. import inference
        if isinstance(model, (list, tuple)) and model and all(
                callable(m) and not isinstance(m, (str, inference.Config,
                                                   inference.Predictor))
                for m in model):
            # one lane per callable — the deterministic failover seam
            if devices is not None:
                raise InvalidArgumentError(
                    "a list-of-callables model already fixes the lane "
                    "count; don't pass devices too")
            self._signature = self._spec_signature(input_spec)
            self._set_expect()
            self._lanes = [_Lane(self, i, m, None, None)
                           for i, m in enumerate(model)]
            return
        predictor = None
        if isinstance(model, str):
            model = inference.Config(model)
        if isinstance(model, inference.Config):
            cfg_model = model
        elif isinstance(model, inference.Predictor):
            predictor = model
            cfg_model = None
        elif callable(model):
            cfg_model = None
        else:
            raise InvalidArgumentError(
                f"InferenceEngine: model must be a path, inference.Config, "
                f"Predictor, callable(s), got {type(model).__name__}")
        if devices is None:
            # the flag is a fleet-wide default for ARTIFACT engines only:
            # a user-built Predictor or callable stays single-lane unless
            # the caller passes devices= explicitly (replicating it behind
            # the caller's back would be a surprise, and a callable may
            # not be thread-safe)
            if cfg_model is not None:
                devices = str(flag("FLAGS_serving_devices")).strip() or "all"
        devs = (inference.resolve_devices(devices)
                if devices is not None else [None])
        if cfg_model is not None:
            predictor = inference.create_predictor(cfg_model,
                                                   device=devs[0])
            lane0 = predictor
        elif predictor is not None and devs[0] is not None:
            # same policy as the config copy above: never mutate the
            # caller's Predictor — pin a clone, leave theirs untouched
            lane0 = predictor.clone_for_device(devs[0])
        else:
            lane0 = predictor
        if predictor is not None:
            self._signature = predictor.input_signature()
            replicas = [lane0] + [predictor.clone_for_device(d)
                                  for d in devs[1:]]
            self._set_expect()
            self._lanes = [_Lane(self, i, p.run_device, p, p.device)
                           for i, p in enumerate(replicas)]
        else:
            self._signature = self._spec_signature(input_spec)
            self._set_expect()
            self._lanes = [_Lane(self, i, model, None, d)
                           for i, d in enumerate(devs)]

    def _set_expect(self):
        from ..inference import format_input_sig
        self._expect = (", ".join(format_input_sig(*s)
                                  for s in self._signature)
                        if self._signature else "")

    @staticmethod
    def _spec_signature(input_spec):
        """Optional signature for callable-backed engines: a list of
        InputSpec or (shape, dtype) pairs; None disables deep validation
        (and warmup, which needs concrete trailing dims)."""
        if input_spec is None:
            return None
        sig = []
        for i, spec in enumerate(input_spec):
            shape = getattr(spec, "shape", None)
            dtype = getattr(spec, "dtype", None)
            if shape is None:
                shape, dtype = spec
            dims = tuple(None if (d is None or d == -1) else int(d)
                         for d in shape)
            sig.append((getattr(spec, "name", None) or f"input_{i}",
                        dims, np.dtype(dtype) if dtype is not None
                        else np.dtype("float32")))
        return sig

    def _fixed_batch(self) -> Optional[int]:
        if not self._signature:
            return None
        dims0 = [d for _, dims, _ in self._signature if dims
                 for d in [dims[0]]]
        fixed = [d for d in dims0 if d is not None]
        return fixed[0] if fixed else None

    # -- request intake ----------------------------------------------------

    def _validate(self, inputs) -> tuple:
        from ..inference import check_fed_input
        sig = self._signature
        nin = len(sig) if sig else None
        if isinstance(inputs, np.ndarray) or not isinstance(
                inputs, (list, tuple)):
            inputs = [inputs]
        arrays = [np.asarray(a) for a in inputs]
        if nin is not None:
            expect = self._expect
            if len(arrays) != nin:
                raise InvalidArgumentError(
                    f"{self.name}: model expects {nin} input(s) "
                    f"[{expect}] but {len(arrays)} were submitted")
            try:
                # shared checker (same one Predictor.run uses), with the
                # batch axis exempt — the engine owns that dimension
                arrays = [check_fed_input(arr, n, dims, dtype,
                                          skip_batch_dim=True,
                                          ctx=self.name, expect=expect)
                          for arr, (n, dims, dtype) in zip(arrays, sig)]
            except ValueError as e:
                raise InvalidArgumentError(str(e)) from None
        rows = {int(a.shape[0]) for a in arrays if a.ndim >= 1}
        if len(rows) != 1:
            raise InvalidArgumentError(
                f"{self.name}: all inputs must share the leading batch "
                f"dim, got {[tuple(a.shape) for a in arrays]}")
        n = rows.pop()
        if n < 1:
            raise InvalidArgumentError(f"{self.name}: empty request")
        if n > self._cfg.max_batch_size:
            raise InvalidArgumentError(
                f"{self.name}: request batch {n} exceeds max_batch_size "
                f"{self._cfg.max_batch_size}; split the request")
        return arrays, n

    def submit(self, inputs, timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one request (arrays with a leading batch dim); returns a
        Future of the per-request output list. Raises `EngineOverloaded`
        when the queue is at max_queue_depth."""
        from . import EngineOverloaded
        with RecordEvent("serving::submit"):
            arrays, rows = self._validate(inputs)
            t = _now_ms()
            tmo = (self._cfg.request_timeout_ms if timeout_ms is None
                   else float(timeout_ms))
            # 0/None disables the deadline; a negative budget (caller's
            # remaining time already spent) expires immediately at pop
            req = _Request(arrays, rows, Future(),
                           None if not tmo else t + tmo, t)
            with self._cv:
                if self._closed:
                    raise UnavailableError(
                        f"{self.name}: engine is shut down")
                if len(self._queue) >= self._cfg.max_queue_depth:
                    monitor.stat_add("STAT_serving_rejected")
                    raise EngineOverloaded(
                        f"{self.name}: queue depth "
                        f"{self._cfg.max_queue_depth} reached "
                        f"({len(self._queue)} pending); shed load or "
                        f"raise FLAGS_serving_max_queue_depth")
                # span AFTER the admission checks: a rejected submit must
                # not leave an orphan flow-start polluting the bounded
                # trace ring. "queued" stamps here — queue time starts at
                # admission — and the flow arrow leaves this submit scope
                req.span = spans.start(self.name)
                self._queue.append(req)
                monitor.stat_add("STAT_serving_queue_depth")
                self._cv.notify_all()
            monitor.stat_add("STAT_serving_requests")
            return req.future

    def run(self, inputs, timeout_ms: Optional[float] = None) -> List:
        """Synchronous submit: blocks for this request's result."""
        return self.submit(inputs, timeout_ms=timeout_ms).result()

    # -- collector ---------------------------------------------------------

    def _peek_live(self) -> Optional[_Request]:
        """Drop expired/cancelled requests from the queue head and return
        the first live one WITHOUT popping it (so the caller can size-check
        before claiming). Caller holds the lock."""
        while self._queue:
            req = self._queue[0]
            if req.deadline_ms is not None and _now_ms() > req.deadline_ms:
                self._queue.popleft()
                monitor.stat_sub("STAT_serving_queue_depth")
                monitor.stat_add("STAT_serving_timeouts")
                try:
                    req.future.set_exception(ExecutionTimeoutError(
                        f"{self.name}: request expired after "
                        f"{_now_ms() - req.t_enqueue_ms:.1f}ms in queue"))
                except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
                    pass
                continue
            if req.future.cancelled():
                self._queue.popleft()
                monitor.stat_sub("STAT_serving_queue_depth")
                continue
            return req
        return None

    def _take(self) -> Optional[_Request]:
        """Pop + claim the queue head; None if a racing cancel won.
        Caller holds the lock and has peeked the head."""
        req = self._queue.popleft()
        monitor.stat_sub("STAT_serving_queue_depth")
        if not req.future.set_running_or_notify_cancel():
            return None
        if req.span is not None:
            req.span.stamp("claimed")
        return req

    def _collect(self) -> Optional[List[_Request]]:
        """Block for the next batch: first live request opens the window,
        co-riders join until max_batch_size or max_batch_delay_ms. A
        request that would overflow the batch stays queued (peek before
        take), so rows never exceed the largest bucket."""
        cfg = self._cfg
        with self._cv:
            while not self._queue and not self._closed:
                self._cv.wait()
            if not self._queue and self._closed:
                return None
            first = None
            while first is None:
                if self._peek_live() is None:
                    return []  # nothing live; outer loop re-waits
                first = self._take()
            batch = [first]
            rows = first.rows
            window_end = _now_ms() + cfg.max_batch_delay_ms
            while rows < cfg.max_batch_size:
                head = self._peek_live() if self._queue else None
                if head is not None:
                    if rows + head.rows > cfg.max_batch_size:
                        break
                    got = self._take()
                    if got is None:
                        continue
                    batch.append(got)
                    rows += got.rows
                else:
                    remain = window_end - _now_ms()
                    if remain <= 0 or self._closed:
                        break
                    self._cv.wait(remain / 1000.0)
                    if not self._queue and _now_ms() >= window_end:
                        break
            return batch

    def _maybe_restart_lane(self, lane: _Lane) -> None:
        """Rebuild one dead lane slot in place (ISSUE 15): fresh
        dispatcher/completer threads around the SAME replica/device —
        the replica's jit wrapper keeps its compiled executables, so a
        restarted lane re-serves without a single new trace. Gated by
        FLAGS_serving_lane_restarts (0 = legacy permanent death), with
        per-slot exponential backoff (FLAGS_gen_restart_backoff_ms
        base, the shared restart primitive); a lane that exhausts its
        budget stays down, and all-lanes-down still closes the engine.
        Runs on the dying lane's own thread, after `_die` failed that
        lane's in-flight work."""
        if not lane.will_restart:  # reservation made in _die's locked
            return                 # section (or none: legacy death)
        try:
            # unblock the dead lane's surviving twin thread (the
            # dispatcher when the completer died, and vice versa): a
            # replaced lane's threads must exit, not leak blocked on
            # queues nobody will ever drain
            lane.inbox.put(None)
            # the death (and its quiet-window verdict) was already
            # noted on this slot's shared backoff in _die's reservation
            delay = self._lane_backoffs[lane.index].next_delay_ms()
            if delay:
                time.sleep(delay / 1000.0)
            fresh = _Lane(self, lane.index, lane.runner, lane.predictor,
                          lane.device)
            fresh.restarts = 1 if lane.quiet_death else lane.restarts + 1
            # accounting continuity: the slot's compile ledger and
            # throughput totals describe the (device, bucket) history,
            # not one thread generation — carrying them forward keeps
            # the exactly-once ledger exact (a callable lane's
            # first-dispatch compile marker must not re-fire)
            fresh.bucket_compiles = dict(lane.bucket_compiles)
            fresh.batches = lane.batches
            fresh.rows = lane.rows
            # start BEFORE the swap: once the lane is visible in
            # self._lanes, a racing shutdown() may Thread.join() it —
            # joining a never-started thread raises out of shutdown
            fresh.start()
            with self._cv:
                if self._closed:
                    fresh.inbox.put(None)  # drain sentinel: the threads
                    return                 # we just started exit clean
                self._lanes[lane.index] = fresh
            monitor.stat_add("STAT_serving_lane_restarts")
        except BaseException as e:  # noqa: BLE001
            # a failed rebuild (e.g. thread-start refusal under the
            # very resource exhaustion that killed the lane) degrades
            # to legacy permanent lane death — it must NOT propagate
            # into the dying thread's death path, which still has its
            # own exit sentinels to post
            flight_recorder.dump("serving_lane_restart_failed", {
                "engine": self.name, "lane": lane.index,
                "error": repr(e)})
        finally:
            with self._cv:
                self._restarting -= 1
                self._cv.notify_all()

    def _wait_capacity(self) -> bool:
        """Block until some alive lane has a free in-flight slot — BEFORE
        claiming requests from the queue, so backpressure stays at the
        front door (submit sees true depth → EngineOverloaded) instead of
        leaking into lane inboxes. False = every lane is dead (and none
        is mid-restart)."""
        with self._cv:
            while True:
                alive = [l for l in self._lanes if l.alive]
                if not alive and self._restarting == 0:
                    return False
                if any(l.inflight < self._cfg.max_inflight for l in alive):
                    return True
                self._cv.wait()

    def _route(self, batch: List[_Request]) -> None:
        """Hand a claimed batch to the best lane: least in-flight, ties
        broken round-robin so equal lanes share warm-cache traffic."""
        with self._cv:
            while True:
                alive = [l for l in self._lanes if l.alive]
                if not alive:
                    if self._restarting:
                        self._cv.wait()  # a lane is mid-rebuild: hold
                        continue         # the batch for it
                    raise UnavailableError(
                        f"{self.name}: all {len(self._lanes)} dispatch "
                        f"lanes dead")
                ready = [l for l in alive
                         if l.inflight < self._cfg.max_inflight]
                if ready:
                    n = len(self._lanes)
                    lane = min(ready, key=lambda l: (
                        l.inflight, (l.index - self._rr) % n))
                    lane.inflight += 1
                    self._rr = (lane.index + 1) % n
                    self._inflight_hist.observe(lane.inflight)
                    # put under _cv: lane death drains its inbox under the
                    # same lock, so a batch can never land in a dead inbox
                    lane.inbox.put(batch)
                    return
                self._cv.wait()

    def _collector_loop(self):
        batch = None
        try:
            while True:
                if not self._wait_capacity():
                    raise UnavailableError(
                        f"{self.name}: all {len(self._lanes)} dispatch "
                        f"lanes dead")
                batch = self._collect()
                if batch is None:
                    return  # closed + drained
                if batch:
                    # the collector's own trace track: scope spans the
                    # routing decision INCLUDING any wait for lane
                    # capacity (visible backpressure in the timeline)
                    with RecordEvent(
                            f"serving::route[n={len(batch)}]"):
                        self._route(batch)
                batch = None
        except BaseException as e:  # noqa: BLE001 — never hang submitters
            # fail BOTH the already-claimed batch and everything still
            # queued, or their submitters block on result() forever
            stranded = list(batch or [])
            with self._cv:
                self._closed = True
                while self._queue:
                    stranded.append(self._queue.popleft())
                    monitor.stat_sub("STAT_serving_queue_depth")
                self._cv.notify_all()
            for req in stranded:
                try:
                    req.future.set_exception(UnavailableError(
                        f"{self.name}: collector died: {e!r}"))
                except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
                    pass
            flight_recorder.dump("serving_collector_death", {
                "engine": self.name, "error": repr(e),
                "stranded_requests": len(stranded)})
            if not isinstance(e, UnavailableError):
                raise
        finally:
            for lane in self._lanes:
                lane.inbox.put(None)  # drain sentinel: lanes finish + exit

    # -- execution helpers -------------------------------------------------

    def _bucket_for(self, rows: int) -> int:
        for b in self._cfg.batch_buckets:
            if b >= rows:
                return b
        return self._cfg.batch_buckets[-1]

    def _warmup(self):
        """Compile every (device, bucket) pair up front so no live request
        pays a compile on any lane. Needs concrete trailing dims; silently
        skipped otherwise."""
        if not self._signature:
            return
        shapes = []
        for _, dims, dtype in self._signature:
            if dims is None or any(d is None for d in dims[1:]):
                return
            shapes.append((tuple(dims[1:]), dtype or np.dtype("float32")))
        with RecordEvent("serving::warmup"):
            if len(self._lanes) == 1:
                self._lanes[0].warm(shapes)
                return
            # lanes are independent replicas (own jit wrapper + run lock):
            # warm them concurrently or constructor latency scales with
            # the device count (N lanes x buckets sequential compiles)
            errs = []

            def _warm(lane):
                try:
                    lane.warm(shapes)
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=_warm, args=(lane,),
                                        name=f"{self.name}-warm{lane.index}")
                       for lane in self._lanes]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                raise errs[0]

    # -- lifecycle / introspection -----------------------------------------

    def stats(self) -> dict:
        """Engine-local snapshot: per-bucket compile/batch/occupancy, live
        queue depth, per-lane state, and latency/in-flight histograms."""
        with self._cv:
            depth = len(self._queue)
            lanes = [{"index": l.index,
                      "device": str(l.device) if l.device is not None
                      else None,
                      "alive": l.alive,
                      "restarts": l.restarts,
                      "inflight": l.inflight} for l in self._lanes]
        with self._stats_lock:
            buckets = {b: dict(s) for b, s in self._bucket_stats.items()}
            for snap, l in zip(lanes, self._lanes):
                snap["batches"] = l.batches
                snap["rows"] = l.rows
                snap["bucket_compiles"] = dict(l.bucket_compiles)
        slots = sum(b * s["batches"] for b, s in buckets.items())
        served = sum(s["rows"] for s in buckets.values())
        # weight-only-quantized artifact? (Predictor read the .pdmeta
        # manifest and keeps int8/int4 weights device-resident) — the
        # capacity-planning signal next to the per-lane ledger
        qinfo = None
        for lane in self._lanes:
            getq = getattr(lane.predictor, "quant_info", None)
            if getq is not None:
                qinfo = getq()
                break
        return {
            "buckets": buckets,
            "lanes": lanes,
            "quantized_weights": qinfo,
            "queue_depth": depth,
            "rows_served": served,
            "mean_occupancy": round(served / slots, 4) if slots else 0.0,
            "latency_ms": self._hist.snapshot(),
            "inflight_depth": self._inflight_hist.snapshot(),
            # per-phase attribution (process-global across engines, like
            # every STAT counter; per-engine e2e is latency_ms above)
            "phases": spans.phase_snapshot(),
        }

    def health(self) -> dict:
        """Readiness verdict for `/readyz`: can this engine take traffic
        RIGHT NOW? Ready = warmup done, not draining/closed, ≥1 live
        lane, intake queue below the rejection threshold. Always carries
        per-lane detail so a router can drain or route around a sick
        replica instead of just dropping it."""
        with self._cv:
            depth = len(self._queue)
            draining = self._closed
            lanes = [{"index": l.index, "alive": l.alive,
                      "inflight": l.inflight} for l in self._lanes]
        live = sum(1 for l in lanes if l["alive"])
        limit = self._cfg.max_queue_depth
        warmed = self._warmed
        if draining:
            reason = "draining"
        elif not warmed:
            reason = "warming up"
        elif live == 0:
            reason = "no live lanes"
        elif depth >= limit:
            reason = "queue at rejection threshold"
        else:
            reason = "ok"
        return {"ready": reason == "ok", "reason": reason,
                "warmup_complete": warmed, "draining": draining,
                "live_lanes": live, "queue_depth": depth,
                "queue_limit": limit, "lanes": lanes}

    def shutdown(self, drain: bool = True, timeout_s: Optional[float] = None):
        """Stop intake; by default the collector routes every queued
        request and the lanes finish them before exiting. With
        drain=False pending futures fail fast (in-flight device batches
        still complete)."""
        with self._cv:
            self._closed = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    monitor.stat_sub("STAT_serving_queue_depth")
                    try:
                        req.future.set_exception(UnavailableError(
                            f"{self.name}: engine shut down"))
                    except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
                        pass
            self._cv.notify_all()
        # one deadline for the WHOLE shutdown: timeout_s bounds the caller's
        # wait, not each of the 1 + 2*lanes joins separately
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        self._collector.join(None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
        for lane in self._lanes:
            lane.join(deadline)
        # a flag/fixed-port HTTP server is shared across engines and
        # stays up; an ephemeral one (explicit metrics_port=0) is this
        # engine's own and would otherwise leak its socket + thread
        exporter.unregister_engine(self)
        if self._owns_metrics_server and self.metrics_server is not None:
            self.metrics_server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
