"""Restart primitives shared by the fault-tolerance layer (ISSUE 15).

Two small, thread-safe building blocks used by BOTH resurrection
consumers — `serving.supervisor.EngineSupervisor` (whole-engine
restarts) and `serving.engine.InferenceEngine` (per-lane restarts) —
so the backoff and crash-storm policies cannot drift apart:

- `RestartBackoff`: exponential delay between consecutive failures
  (base * 2^(n-1), capped at 32x the base), reset explicitly once the
  restarted unit has proven stable. The *caller* sleeps — the policy
  object only computes, so tests can assert the schedule without
  waiting it out.
- `CrashBreaker`: a rolling-window event counter that OPENS (latches)
  once `threshold` failures land inside `window_s`. Open is terminal
  by design: a crash storm means restarts are not fixing the cause,
  and flapping — down, up for one request, down again — burns more
  than staying down and reporting `/readyz` 503 with a reason until an
  operator (or a fresh process) intervenes.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

__all__ = ["RestartBackoff", "CrashBreaker"]

_BACKOFF_CAP_FACTOR = 32


class RestartBackoff:
    """Exponential restart delay: base_ms, 2*base_ms, 4*base_ms, ...
    capped at 32x base; `reset()` returns to the base once the
    restarted unit survives long enough to be trusted again."""

    def __init__(self, base_ms: float):
        self.base_ms = max(0.0, float(base_ms))
        self._lock = threading.Lock()
        self._consecutive = 0
        self._last_death: Optional[float] = None

    def note_death(self, quiet_after_s: float,
                   now: Optional[float] = None) -> bool:
        """Record one failure instant. A gap longer than
        `quiet_after_s` since the PREVIOUS failure means the restarted
        unit proved stable: the escalation resets and True is returned
        (callers restore the unit's restart budget on it too) — only
        CONSECUTIVE failures escalate. This is THE quiet-window policy,
        shared by the engine supervisor and the per-lane restarts so
        the two cannot drift."""
        t = time.monotonic() if now is None else now
        with self._lock:
            last, self._last_death = self._last_death, t
            if last is not None and t - last > quiet_after_s:
                self._consecutive = 0
                return True
            return False

    def next_delay_ms(self) -> float:
        """Delay to wait before the NEXT restart attempt; each call
        counts one failure."""
        with self._lock:
            n = self._consecutive
            self._consecutive += 1
        return min(self.base_ms * (2 ** n),
                   self.base_ms * _BACKOFF_CAP_FACTOR)

    @property
    def max_delay_ms(self) -> float:
        """The escalation ceiling — callers sizing a wait-for-restart
        deadline derive it from THIS, not a constant, so a flag-scaled
        backoff can't outlive the waiter."""
        return self.base_ms * _BACKOFF_CAP_FACTOR

    @property
    def consecutive(self) -> int:
        with self._lock:
            return self._consecutive

    def reset(self) -> None:
        with self._lock:
            self._consecutive = 0


class CrashBreaker:
    """N failures in a rolling window opens the breaker — permanently,
    until `reset()` (operator action / process restart)."""

    def __init__(self, threshold: int, window_s: float):
        self.threshold = max(1, int(threshold))
        self.window_s = max(0.0, float(window_s))
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._open = False
        self._opened_at: Optional[float] = None

    def record(self, now: Optional[float] = None) -> bool:
        """Count one failure; returns True the moment the breaker
        opens (and on every later record while open)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            if self._open:
                return True
            self._events.append(t)
            while self._events and t - self._events[0] > self.window_s:
                self._events.popleft()
            if len(self._events) >= self.threshold:
                self._open = True
                self._opened_at = t
            return self._open

    def trip(self, now: Optional[float] = None) -> None:
        """Latch the breaker open directly — for failure modes the
        rolling window cannot count reliably (e.g. rebuild attempts
        that each fail SLOWER than the window accumulates events)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            if not self._open:
                self._open = True
                self._opened_at = t

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._open

    def state(self) -> dict:
        with self._lock:
            return {"open": self._open,
                    "threshold": self.threshold,
                    "window_s": self.window_s,
                    "recent_events": len(self._events),
                    "open_for_s": (round(time.monotonic()
                                         - self._opened_at, 3)
                                   if self._open else None)}

    def reset(self) -> None:
        with self._lock:
            self._open = False
            self._opened_at = None
            self._events.clear()
