"""Deterministic, flag-gated fault injection for the serving stack
(ISSUE 15).

Every hardened failure path in the generation engine — decode-step
exceptions, prefill exceptions, poisoned (non-finite) logits, allocator
exhaustion, slow steps — used to be testable only through hand-crafted
monkeypatching of private seams. This registry names those seams as
**failpoints** and arms them from one flag, so the supervisor, the
chaos soak, and `bench.py --mode recovery` can inject the exact fault
class they exercise, deterministically, with zero code changes:

    FLAGS_failpoints = "decode_step_raise@3"            # 3rd hit only
    FLAGS_failpoints = "decode_poison_nan@every:5"      # every 5th hit
    FLAGS_failpoints = "slow_step_ms@every:2:40"        # arg = 40 ms
    FLAGS_failpoints = "prefill_raise@1;alloc_exhaust@every:3"

Grammar: ';'-separated `site@trigger[:arg]` terms. `trigger` is either
a plain integer `N` — fire on the Nth hit of that site ONLY (one-shot;
hit counters are process-wide, so a restarted engine does NOT re-fire
an already-spent one-shot — exactly the semantics a supervised-restart
test needs) — or `every:K` — fire on every Kth hit. `arg` is one
optional float the site interprets (today only `slow_step_ms` reads
it: the sleep in milliseconds).

Sites (`SITES`):

- `decode_step_raise` — raise `InjectedFault` before the decode/verify
  dispatch (engine-fatal: the pools are donated into that call).
- `prefill_raise`    — raise `InjectedFault` before a prefill dispatch
  (engine-fatal, same donation contract).
- `decode_poison_nan` — mark one live slot's logits non-finite after
  the step (exercises poison isolation, NOT engine death).
- `alloc_exhaust`    — force the admission pass to treat the page pool
  as exhausted (DEFER_PAGES without actually draining it).
- `slow_step_ms`     — sleep `arg` ms at the top of the step (SLO /
  burn-rate exercises).
- `kv_tier.promote_upload` — abandon a host-tier promotion before the
  next upload chunk's dispatch (ISSUE 18): the admission zeroes the
  partially-written target pages and falls back to cold prefill. Fired
  BEFORE the donating scatter, so no pool is ever half-consumed.
- `kv_tier.demote_gather` — fail the off-device page gather at
  demotion time: the eviction proceeds plain (content discarded), the
  PR 12 behavior exactly — no leak on either tier.

Cost discipline: with `FLAGS_failpoints` unset (the default, and every
production deployment), `fire()` is one flag read + one emptiness check
— no lock, no parsing, no counters. Hit counting starts only while a
spec is armed. `reset()` zeroes the counters and the parse cache
(tests, bench arms).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..framework import monitor
from ..framework.errors import InvalidArgumentError
from ..framework.flags import flag

__all__ = ["SITES", "InjectedFault", "fire", "maybe_raise", "reset",
           "snapshot"]

SITES = ("decode_step_raise", "prefill_raise", "decode_poison_nan",
         "alloc_exhaust", "slow_step_ms", "kv_tier.promote_upload",
         "kv_tier.demote_gather")


class InjectedFault(RuntimeError):
    """The exception an armed *_raise failpoint throws — a distinct
    type so tests and postmortems can tell an injected fault from a
    real one at a glance."""


def _parse(spec: str) -> Dict[str, Tuple[str, int, Optional[float]]]:
    """{site: (mode, n, arg)} — mode "nth" (one-shot on hit n) or
    "every" (every nth hit). A malformed spec raises immediately: a
    typo'd failpoint that silently never fires would invalidate the
    very test that armed it."""
    out: Dict[str, Tuple[str, int, Optional[float]]] = {}
    for term in spec.split(";"):
        term = term.strip()
        if not term:
            continue
        if "@" not in term:
            raise InvalidArgumentError(
                f"FLAGS_failpoints term {term!r} lacks '@trigger' "
                f"(spell it site@N, site@N:arg, site@every:K or "
                f"site@every:K:arg)")
        site, trig = term.split("@", 1)
        site = site.strip()
        if site not in SITES:
            raise InvalidArgumentError(
                f"unknown failpoint site {site!r}; known: {SITES}")
        if site in out:
            raise InvalidArgumentError(
                f"failpoint site {site!r} appears twice in "
                f"FLAGS_failpoints — one trigger per site")
        parts = [p.strip() for p in trig.split(":")]
        arg: Optional[float] = None
        try:
            if parts[0] == "every":
                if len(parts) < 2:
                    raise ValueError("every needs a K")
                mode, n = "every", int(parts[1])
                if len(parts) > 2:
                    arg = float(parts[2])
            else:
                mode, n = "nth", int(parts[0])
                if len(parts) > 1:
                    arg = float(parts[1])
        except ValueError as e:
            raise InvalidArgumentError(
                f"FLAGS_failpoints term {term!r}: bad trigger "
                f"({e})") from None
        if n < 1:
            raise InvalidArgumentError(
                f"FLAGS_failpoints term {term!r}: trigger count must "
                f"be >= 1")
        out[site] = (mode, n, arg)
    return out


class _Registry:
    """Process-wide armed-spec cache + per-site hit/fired counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._src: Optional[str] = None   # raw spec last parsed
        self._armed: Dict[str, Tuple[str, int, Optional[float]]] = {}
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}

    def fire(self, site: str) -> Optional[float]:
        """One hit at `site`; returns the trigger's arg (or 0.0 when
        it has none) if this hit fires, else None. The fast path —
        flag unset — is a dict read + strip, nothing else."""
        spec = str(flag("FLAGS_failpoints"))
        if not spec.strip():
            return None
        with self._lock:
            if spec != self._src:
                # re-arming does NOT reset hit counters: a one-shot
                # spent before a flag rewrite stays spent (reset() is
                # the explicit way to start a fresh schedule)
                self._armed = _parse(spec)
                self._src = spec
            trig = self._armed.get(site)
            if trig is None:
                return None
            self._hits[site] = hit = self._hits.get(site, 0) + 1
            mode, n, arg = trig
            hits_now = (hit == n) if mode == "nth" else (hit % n == 0)
            if not hits_now:
                return None
            self._fired[site] = self._fired.get(site, 0) + 1
        monitor.stat_add("STAT_failpoints_fired")
        return 0.0 if arg is None else arg

    def reset(self) -> None:
        with self._lock:
            self._src = None
            self._armed = {}
            self._hits = {}
            self._fired = {}

    def snapshot(self) -> dict:
        with self._lock:
            return {"armed": dict(self._armed),
                    "hits": dict(self._hits),
                    "fired": dict(self._fired)}


_REG = _Registry()


def fire(site: str) -> Optional[float]:
    """Count one hit at `site`; non-None (the trigger arg) iff this
    hit fires. Never raises on the hot path when the flag is unset."""
    return _REG.fire(site)


def maybe_raise(site: str) -> None:
    """`fire()` + raise `InjectedFault` when triggered — the helper
    the *_raise sites use so every injected exception carries the
    site name."""
    if _REG.fire(site) is not None:
        raise InjectedFault(f"failpoint {site} fired "
                            f"(FLAGS_failpoints="
                            f"{str(flag('FLAGS_failpoints')).strip()!r})")


def reset() -> None:
    """Zero every hit/fired counter and drop the parse cache (tests /
    bench arms start a fresh schedule)."""
    _REG.reset()


def snapshot() -> dict:
    """{armed, hits, fired} — the registry's current accounting."""
    return _REG.snapshot()
