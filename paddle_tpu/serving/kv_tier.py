"""Host-RAM demotion tier under the prefix cache (ISSUE 18).

HBM is the scarcest resource in the system, and before this tier a
prefix-cache page was binary: resident in HBM or zeroed and gone —
every eviction converted a future TTFT win back into a full prefill.
This module is the second tier the paper's own memory design calls for
(the L1 allocator tiers / CUDAPinnedPlace staging path whose
device-bound half PR 1's DeviceFeeder double-buffer reproduced): when
`PrefixCache` eviction would free a cold chain's pages, the engine
*demotes* them instead — a jitted gather pulls the raw page blocks
(and, in int8 mode, the per-(layer, head) fp32 scale rows) off-device
into this bounded host store, keyed by the chain's blake2b digests,
and the HBM pages are zeroed-and-freed exactly as before. A later
lookup that misses HBM but hits here *promotes*: the pages re-upload
through a double-buffered `jax.device_put` pipeline overlapped with
the tail prefill of the uncovered suffix (the DeviceFeeder pattern
pointed the other way), so a revisit costs ~one tail prefill instead
of a full re-prefill.

Contents are stored RAW — int8 pages keep their integer bytes and
their fp32 scale rows side by side — so a promote re-uploads
bit-identical content with no requantization step. That is the whole
token-identity guarantee: a promoted chain decodes exactly like a
never-evicted one (the PR 9 scale-grid poisoning class, now across
tiers; see tests/test_kv_tier.py).

Budget: the tier owns its own byte budget (`FLAGS_kv_tier_host_bytes`)
with LRU eviction — demote-of-demoted is the final eviction, the
entry's content is gone for good (audit code KV_TIER_EVICT). `put`
returns the evicted digests so the `PrefixCache` can drop the
corresponding host-state chain nodes in the same step; an entry that
alone exceeds the budget is refused outright (stored nowhere, plain
eviction semantics apply upstream).

Threading: single-writer like the allocator and the prefix index — the
engine's STEP thread owns every mutation (demote at eviction, pop at
promotion, LRU eviction inside put). Scraper/submit threads read the
plain-int counters and `host_bytes` GIL-atomically via `stats()`; the
`_TRACECHECK_THREADS` declaration below states that contract so the
lock-discipline pass (tools/tracecheck) machine-checks it: every
mutating method is declared step-thread-only, and a mutation reachable
from the caller surface would be flagged.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..framework import monitor

__all__ = ["HostEntry", "HostTier"]


class HostEntry:
    """One demoted page's host copy: raw K/V page blocks
    `[L, H, page_size, D]` plus (int8 mode) the per-(layer, head) fp32
    scale rows `[L, H]` — raw bytes in, raw bytes out, so the
    round-trip is exact."""

    __slots__ = ("k", "v", "ks", "vs", "nbytes")

    def __init__(self, k, v, ks=None, vs=None):
        self.k = np.asarray(k)
        self.v = np.asarray(v)
        self.ks = None if ks is None else np.asarray(ks)
        self.vs = None if vs is None else np.asarray(vs)
        self.nbytes = int(
            self.k.nbytes + self.v.nbytes
            + (0 if self.ks is None else self.ks.nbytes)
            + (0 if self.vs is None else self.vs.nbytes))


class HostTier:
    """Bounded, LRU-evicting host-RAM store of demoted prefix-cache
    pages for ONE engine, keyed by chain digest.

    The engine's step thread is the only writer (see module docstring);
    the declaration below is read by the tracecheck lock-discipline
    pass: these methods run ONLY on the declared foreign thread, so
    their lock-free mutations are single-entry by contract."""

    _TRACECHECK_THREADS = {
        "step": ("put", "get", "pop", "note_promotion", "note_hit",
                 "note_abandon"),
    }

    def __init__(self, max_bytes: int, engine: str = "generation"):
        self.engine = engine
        self.max_bytes = int(max_bytes)
        self._entries: "OrderedDict[bytes, HostEntry]" = OrderedDict()
        self._bytes = 0
        # plain-int counters: step thread writes, scrapers read
        # GIL-atomically (stats() below)
        self.demotions = 0    # entries ever stored
        self.promotions = 0   # pages re-uploaded to HBM
        self.evictions = 0    # entries finally dropped (LRU / cascade)
        self.hits = 0         # admissions that matched >= 1 host page
        self.abandons = 0     # promotions abandoned mid-upload
        self.rejects = 0      # puts refused (entry alone over budget)

    # -- store mutation (step thread only) ---------------------------------

    def put(self, digest: bytes, entry: HostEntry,
            protect: Iterable[bytes] = ()) -> Tuple[bool, List[bytes]]:
        """Store one demoted page under `digest` (MRU), LRU-evicting
        other entries until the byte budget holds. Returns
        `(stored, evicted_digests)` — the caller drops the chain nodes
        of every evicted digest (demote-of-demoted = final eviction).
        `protect` digests (an in-flight admission's matched host run)
        are never evicted, even if the budget temporarily overshoots.
        An entry that alone exceeds the budget is refused."""
        if entry.nbytes > self.max_bytes:
            self.rejects += 1
            return False, []
        old = self._entries.pop(digest, None)
        if old is not None:
            self._bytes -= old.nbytes
        self._entries[digest] = entry
        self._bytes += entry.nbytes
        self.demotions += 1
        monitor.stat_add("STAT_kv_tier_demotions")
        evicted: List[bytes] = []
        if self._bytes > self.max_bytes:
            keep = set(protect)
            keep.add(digest)
            for d in list(self._entries):
                if self._bytes <= self.max_bytes:
                    break
                if d in keep:
                    continue
                ev = self._entries.pop(d)
                self._bytes -= ev.nbytes
                self.evictions += 1
                monitor.stat_add("STAT_kv_tier_evictions")
                evicted.append(d)
        monitor.stat_set("STAT_kv_tier_host_bytes", self._bytes)
        return True, evicted

    def get(self, digest: bytes) -> Optional[HostEntry]:
        """Entry for `digest` (touches LRU recency) or None."""
        entry = self._entries.get(digest)
        if entry is not None:
            self._entries.move_to_end(digest)
        return entry

    def pop(self, digest: bytes,
            final: bool = False) -> Optional[HostEntry]:
        """Remove and return the entry for `digest` (None if absent).
        Promotion uses move semantics — the host copy leaves the store
        as its content heads back to HBM, holding the one-copy
        invariant. `final=True` counts the pop as a tier eviction (a
        cascade drop of an orphaned descendant, or an abandon discard)
        rather than a promotion-side move."""
        entry = self._entries.pop(digest, None)
        if entry is not None:
            self._bytes -= entry.nbytes
            if final:
                self.evictions += 1
                monitor.stat_add("STAT_kv_tier_evictions")
            monitor.stat_set("STAT_kv_tier_host_bytes", self._bytes)
        return entry

    def note_promotion(self, pages: int) -> None:
        """Count `pages` pages re-uploaded to HBM (one admission)."""
        self.promotions += int(pages)
        monitor.stat_add("STAT_kv_tier_promotions", int(pages))

    def note_hit(self) -> None:
        """Count one admission that matched >= 1 host-tier page."""
        self.hits += 1
        monitor.stat_add("STAT_kv_tier_hits")

    def note_abandon(self) -> None:
        """Count one promotion abandoned mid-upload (fault / failpoint
        — the admission fell back to cold prefill)."""
        self.abandons += 1
        monitor.stat_add("STAT_kv_tier_abandons")

    # -- read surface (any thread; GIL-atomic reads) -----------------------

    @property
    def host_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._entries

    def digests(self) -> List[bytes]:
        """Snapshot of stored digests, LRU-first (tests/bench leak
        accounting)."""
        return list(self._entries)

    def stats(self) -> Dict[str, int]:
        """Scraper-safe snapshot (each field one GIL-atomic read)."""
        return {
            "max_bytes": self.max_bytes,
            "host_bytes": self._bytes,
            "entries": len(self._entries),
            "demotions": self.demotions,
            "promotions": self.promotions,
            "evictions": self.evictions,
            "hits": self.hits,
            "abandons": self.abandons,
            "rejects": self.rejects,
        }
