"""High-throughput batched serving (reference `paddle/fluid/inference/`
gave AnalysisPredictor a server-side sibling in Paddle Serving; here the
TPU-native equivalent is an in-process engine, because on accelerators
serving throughput *is* dynamic micro-batching into a small set of
pre-compiled bucketed shapes).

`InferenceEngine` wraps `inference.create_predictor`:

- **micro-batcher** — concurrent `submit()` calls coalesce into one
  device batch under `max_batch_size` / `max_batch_delay_ms`; each call
  returns a `concurrent.futures.Future`.
- **shape bucketing** — batches pad up to configured batch-size buckets
  (default 1/4/16/64) so XLA compiles exactly once per bucket; results
  are sliced back per request, bit-identical to unbatched runs.
- **backpressure & robustness** — bounded queue (`EngineOverloaded`),
  per-request deadlines (`ExecutionTimeoutError`), a worker that
  isolates a poisoned request to its own future, `shutdown()` drains.
- **observability** — `framework.monitor` STAT counters + a streaming
  latency histogram (p50/p99), `profiler.RecordEvent` scopes.
"""
from __future__ import annotations

from ..framework.errors import ResourceExhaustedError


class EngineOverloaded(ResourceExhaustedError):
    """Raised by `InferenceEngine.submit` when the bounded request queue
    is full — explicit load-shedding backpressure, never silent growth."""


from .engine import EngineConfig, InferenceEngine  # noqa: E402

__all__ = ["InferenceEngine", "EngineConfig", "EngineOverloaded"]
