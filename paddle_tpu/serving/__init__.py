"""High-throughput batched serving (reference `paddle/fluid/inference/`
gave AnalysisPredictor a server-side sibling in Paddle Serving; here the
TPU-native equivalent is an in-process engine, because on accelerators
serving throughput *is* dynamic micro-batching into a small set of
pre-compiled bucketed shapes).

`InferenceEngine` wraps `inference.create_predictor`:

- **micro-batcher** — concurrent `submit()` calls coalesce into one
  device batch under `max_batch_size` / `max_batch_delay_ms`; each call
  returns a `concurrent.futures.Future`.
- **pipelined multi-device dispatch** — a shared collector routes
  batches to one dispatch lane per local device (`devices=` / the
  `FLAGS_serving_devices` default), round-robin with a least-inflight
  tiebreak; each lane enqueues the device call asynchronously and a
  completion stage blocks/slices/resolves, so admission, compute, and
  readback overlap (`FLAGS_serving_max_inflight` bounds the pipeline).
- **shape bucketing** — batches pad up to configured batch-size buckets
  (default 1/4/16/64) so XLA compiles exactly once per (device, bucket);
  results are sliced back per request, bit-identical to unbatched runs
  on the same lane+bucket.
- **backpressure & robustness** — bounded queue (`EngineOverloaded`),
  per-request deadlines enforced both while queued AND at completion
  (`ExecutionTimeoutError`), poison isolation per lane, a dead lane
  fails only its own in-flight work and leaves rotation, `shutdown()`
  drains.
- **observability** — `framework.monitor` STAT counters (global +
  per-lane `STAT_serving_lane*`) + streaming latency and in-flight-depth
  histograms, `profiler.RecordEvent` scopes.
- **fault tolerance (ISSUE 15)** — `EngineSupervisor` resurrects a dead
  `GenerationEngine` in place (crash-manifest request replay,
  exactly-once streams, crash-storm breaker, degraded modes), dispatch
  lanes restart per-slot (`FLAGS_serving_lane_restarts`), and
  `failpoints` injects deterministic faults into every hardened seam
  (`FLAGS_failpoints`).
- **router tier (ISSUE 17)** — `Router`: one front door over N
  supervised `GenerationEngine` replicas; prefix-affinity placement
  (blake2b chain digests vs per-replica LRU sketches — session
  stickiness with zero router session state), least-pressure fallback
  on cached `pressure()` snapshots, drain on SLO burn / breaker-open,
  placement-time re-route under typed-failure semantics.
- **warm start (ISSUE 16)** — `ProgramStore`: a keyed on-disk AOT
  executable store; `GenerationEngine` warmup loads serialized
  prefill/tail/decode/verify/cow programs under a content key instead
  of tracing (miss → compile + write back), every load gated by a
  donation-aliasing self-check + numeric smoke probe, refused on
  XLA:CPU (the PR 1 corruption class) unless forced.
"""
from __future__ import annotations

from ..framework.errors import ResourceExhaustedError


class EngineOverloaded(ResourceExhaustedError):
    """Raised by `InferenceEngine.submit` when the bounded request queue
    is full — explicit load-shedding backpressure, never silent growth."""


from . import failpoints  # noqa: E402
from .engine import EngineConfig, InferenceEngine  # noqa: E402
from .generation import (CrashManifest, GenerationConfig,  # noqa: E402
                         GenerationEngine, ReplayEntry, TokenStream)
from .kv_cache import PagedKVCache  # noqa: E402
from .prefix_cache import PrefixCache, chain_digests  # noqa: E402
from .program_store import ProgramStore  # noqa: E402
from .restart import CrashBreaker, RestartBackoff  # noqa: E402
from .router import Router  # noqa: E402
from .spec_decode import NGramProposer  # noqa: E402
from .supervisor import EngineSupervisor  # noqa: E402

__all__ = ["InferenceEngine", "EngineConfig", "EngineOverloaded",
           "EngineSupervisor", "CrashBreaker", "CrashManifest",
           "GenerationEngine", "GenerationConfig", "NGramProposer",
           "PagedKVCache", "PrefixCache", "ProgramStore", "ReplayEntry",
           "RestartBackoff", "Router", "TokenStream", "chain_digests",
           "failpoints"]
