"""Engine resurrection: supervised restart with request replay
(ISSUE 15).

`GenerationEngine` treats any decode/prefill jit exception as
engine-fatal — correctly, because the KV pools were donated into the
failing call — but before this module that verdict stranded every
queued and live request with `UnavailableError` and left the process
needing an external restart, a re-warmup, and a cold KV pool. The
ROADMAP's router tier assumes replicas that heal themselves; the
designs the engine is built on make that cheap:

- **Iteration-level scheduling** (Orca, PR 8) means a mid-decode
  sequence is fully described by `prompt + generated-so-far` — replay
  is just a re-submit whose prompt is the continuation and whose
  budget is the remainder. The rebuilt engine's greedy decode is
  deterministic given the prefix, so survivors finish token-identical
  to a fault-free run.
- **The program-pack compile discipline** (PR 8's jit wrappers +
  ledger, lifted into `_ProgramPack`) means a rebuilt engine reuses
  the dead one's jit wrappers and re-warms from XLA's in-process
  caches: *zero new traces*, ledger-proven, so recovery is pool-rebuild
  + replay-prefill, not minutes of compilation. Rebuilds prefer the
  store (ISSUE 16): the carried pack's `execs` map holds the AOT
  executables the dead engine resolved — store-loaded or live-compiled
  — so a resurrection re-warms through them with zero traces AND zero
  disk loads; and because the supervisor rebuilds with the SAME config,
  a first build (or a pack-less rebuild) that names
  `program_store` loads from disk instead of tracing, which shrinks
  the recovery wall from compile-bound to deserialize-bound.
- **The prefix cache** (PR 12) makes replay prefill near-free for
  shared-prefix traffic: the first replayed prompt re-registers its
  chain and every later replay walks it.

`EngineSupervisor` wraps one engine: on death it receives the
`CrashManifest` the engine's `_die` builds (queued requests verbatim;
live slots as continuations; each entry's caller-held future/stream
preserved), applies exponential backoff (`FLAGS_gen_restart_backoff_ms`
base), rebuilds a fresh engine with the same config — same name, next
`incarnation`, same program pack + step/audit rings, degraded-mode
state carried over — and replays every entry in original admission
order under a per-request retry budget (`FLAGS_gen_retry_limit`;
exceeded → typed `UnavailableError`, audit `RETRY_EXHAUSTED`).

**Exactly-once streams.** `_die` flushes staged tokens before the
manifest is captured, so for a streaming request `delivered ==
len(generated)`. A continuation replay moves those tokens into the
prompt — the new engine streams only NEW tokens: no duplicate, no gap.
When a continuation no longer fits the prefill buckets, a greedy stream
replays from scratch with the first `delivered` tokens suppressed
(greedy re-derivation is byte-identical); a sampled stream in that
corner fails typed instead — regenerated samples would diverge from the
tokens already delivered.

**Crash-storm breaker.** `FLAGS_gen_breaker_threshold` deaths inside
`FLAGS_gen_breaker_window_s` opens the breaker (audit `BREAKER_OPEN`,
`STAT_gen_breaker_open`): the supervisor stays down, pending work fails
typed, and `health()` — the supervisor, not the engine, is the
registered `/readyz` entity — reports 503 with the breaker reason until
an operator intervenes. Flapping burns more than staying down.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..framework import monitor
from ..framework.errors import InvalidArgumentError, UnavailableError
from ..framework.flags import flag
from ..profiler import exporter, slo
from .generation import (CrashManifest, GenerationConfig,
                         GenerationEngine, ReplayEntry, TokenStream)
from .restart import CrashBreaker, RestartBackoff

__all__ = ["EngineSupervisor"]


class EngineSupervisor:
    """Self-healing wrapper around one `GenerationEngine`: same submit
    surface (`submit` / `submit_stream` / `generate`), plus restart,
    replay, breaker and degraded-mode supervision. Register THIS with
    the router tier — its `health()` spans engine generations."""

    def __init__(self, model, config: Optional[GenerationConfig] = None,
                 name: str = "generation", device=None,
                 metrics_port: Optional[int] = None,
                 retry_limit: Optional[int] = None,
                 restart_backoff_ms: Optional[float] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_window_s: Optional[float] = None,
                 **overrides):
        if config is None:
            config = GenerationConfig(**overrides)
        elif overrides:
            raise InvalidArgumentError(
                "pass either a GenerationConfig or keyword overrides, "
                "not both")
        self.name = name
        self._model = model
        self._cfg = config
        self._device = device
        self._retry_limit = int(flag("FLAGS_gen_retry_limit")
                                if retry_limit is None else retry_limit)
        self._backoff = RestartBackoff(
            float(flag("FLAGS_gen_restart_backoff_ms"))
            if restart_backoff_ms is None else float(restart_backoff_ms))
        self._breaker = CrashBreaker(
            int(flag("FLAGS_gen_breaker_threshold"))
            if breaker_threshold is None else int(breaker_threshold),
            float(flag("FLAGS_gen_breaker_window_s"))
            if breaker_window_s is None else float(breaker_window_s))
        # the gate serializes restarts against submits: a submit that
        # races a death blocks briefly and lands on the new engine;
        # _swap_cv wakes submitters parked in _await_recovery once a
        # restart (or a final breaker/shutdown verdict) lands
        self._gate = threading.RLock()
        self._swap_cv = threading.Condition()
        self._closed = False
        self._restarting = False
        self._breaker_reason: Optional[str] = None
        self.incarnation = 0
        self.restarts = 0
        self.replayed = 0
        self.retry_exhausted = 0
        self.replay_impossible = 0
        # entries whose replay target died before they could land on
        # it: they ride the NEXT crash manifest with their retry budget
        # untouched (gate-serialized — only the death handler touches
        # this)
        self._pending_replays = []
        self._last_recovery_ms: Optional[float] = None
        self._replay_ms_total = 0.0
        self._engine = self._build_engine(incarnation=0, carry=None)
        exporter.register_engine(self)
        self._owns_metrics_server = (metrics_port is not None
                                     and int(metrics_port) == 0)
        self.metrics_server = None
        try:
            self.metrics_server = exporter.start_metrics_server(
                metrics_port)
        except Exception:
            self.shutdown(drain=False, timeout_s=5)
            raise

    # -- engine lifecycle ---------------------------------------------------

    def _build_engine(self, incarnation: int,
                      carry: Optional[dict]) -> GenerationEngine:
        import copy
        return GenerationEngine(
            self._model, copy.copy(self._cfg), name=self.name,
            device=self._device, incarnation=incarnation,
            on_death=self._on_engine_death, _carryover=carry)

    def _on_engine_death(self, manifest: CrashManifest) -> None:
        """The dead engine's `_die` hands over here (still on the dying
        step thread): breaker check → backoff → rebuild (same pack →
        zero new traces) → replay in admission order. Runs under the
        gate, so submits block until the new engine serves."""
        t0 = time.perf_counter()
        try:
            self._handle_death(manifest)
        finally:
            # wake submitters parked in _await_recovery on EVERY exit
            # path (restart done, breaker open, shutdown race)
            with self._swap_cv:
                self._swap_cv.notify_all()
        dt = (time.perf_counter() - t0) * 1000.0
        self._last_recovery_ms = dt
        self._replay_ms_total += dt
        monitor.stat_add("STAT_gen_replay_ms", int(round(dt)))

    def _handle_death(self, manifest: CrashManifest) -> None:
        with self._gate:
            self._restarting = True
            try:
                dead = self._engine
                # quiet-window policy (restart.py): an engine that
                # survived a full breaker window earned the base
                # backoff again — only CONSECUTIVE deaths escalate
                self._backoff.note_death(self._breaker.window_s)
                # entries deferred by a death DURING the previous
                # replay pass come first: they were admitted before
                # anything in this manifest
                entries = self._pending_replays + list(manifest.entries)
                self._pending_replays = []
                if self._closed:
                    self._fail_entries(
                        entries,
                        f"{self.name}: supervisor shut down during "
                        f"restart")
                    return
                if self._breaker.record():
                    if self._breaker_reason is None:
                        st = self._breaker.state()
                        self._breaker_reason = (
                            f"crash-storm breaker open: "
                            f">={st['threshold']} engine deaths in "
                            f"{st['window_s']}s (last: "
                            f"{manifest.error!r})")
                        monitor.stat_add("STAT_gen_breaker_open")
                        dead._audit.audit(
                            "BREAKER_OPEN",
                            threshold=st["threshold"],
                            window_s=st["window_s"],
                            error=repr(manifest.error))
                        dead._audit.flush_sink()
                    self._fail_entries(entries,
                                       f"{self.name}: "
                                       f"{self._breaker_reason}")
                    return
                carry = {"pack": dead._pack,
                         "step_log": dead._step_log,
                         "audit": dead._audit,
                         "degraded_spec_off":
                             manifest.degraded_spec_off}
                eng = None
                build_failures = 0
                while eng is None:
                    delay = self._backoff.next_delay_ms()
                    if delay:
                        time.sleep(delay / 1000.0)
                    self.incarnation += 1
                    try:
                        eng = self._build_engine(self.incarnation,
                                                 carry)
                    except Exception as build_e:  # noqa: BLE001
                        # a rebuild that fails (warmup OOM, device
                        # gone) is another death for the breaker —
                        # ALSO capped by consecutive count: failures
                        # slower than the rolling window accumulates
                        # would otherwise spin this loop forever with
                        # the submit gate held
                        build_failures += 1
                        if (self._breaker.record()
                                or build_failures
                                >= self._breaker.threshold):
                            self._breaker.trip()
                            self._breaker_reason = (
                                f"crash-storm breaker open: rebuild "
                                f"keeps failing ({build_e!r})")
                            monitor.stat_add("STAT_gen_breaker_open")
                            self._fail_entries(
                                entries,
                                f"{self.name}: "
                                f"{self._breaker_reason}")
                            return
                self._engine = eng
                self.restarts += 1
                monitor.stat_add("STAT_gen_restarts")
                eng._audit.audit(
                    "ENGINE_RESTART", incarnation=self.incarnation,
                    backoff_ms=round(delay, 1),
                    error=repr(manifest.error),
                    entries=len(entries))
                for entry in entries:
                    self._replay_entry(eng, entry)
                eng._audit.flush_sink()
            finally:
                self._restarting = False

    def _replay_entry(self, eng: GenerationEngine,
                      entry: ReplayEntry) -> None:
        if entry.retries >= self._retry_limit:
            self.retry_exhausted += 1
            eng._audit.audit("RETRY_EXHAUSTED", rid=entry.rid,
                             retries=entry.retries,
                             limit=self._retry_limit,
                             **({"trace": entry.trace_id}
                                if entry.trace_id else {}))
            self._fail_entry(entry, (
                f"{self.name}: request failed permanently — replay "
                f"budget exhausted after {entry.retries} engine "
                f"restart(s) (FLAGS_gen_retry_limit="
                f"{self._retry_limit})"))
            return
        k = len(entry.toks)
        S = int(entry.prompt.size)
        bmax = eng._cfg.prefill_buckets[-1]
        if k and S + k <= bmax:
            # continuation: the generated prefix becomes prompt, the
            # remaining budget becomes max_new — the full sequence the
            # future resolves with is unchanged, and a stream emits
            # only tokens it has not delivered yet. `delivered` can
            # exceed k when THIS entry is itself an interrupted
            # from-scratch replay (tokens past k were delivered by an
            # even earlier incarnation): keep suppressing those.
            prompt = np.concatenate(
                [entry.prompt, np.asarray(entry.toks, np.int32)])
            max_new = entry.max_new - k
            skip = max(0, entry.delivered - k)
        elif k == 0:
            # nothing generated THIS incarnation — but an interrupted
            # from-scratch replay may still owe suppressions for tokens
            # an even earlier incarnation delivered (entry.delivered
            # carries the residue; 0 for a never-delivered request)
            prompt, max_new = entry.prompt, entry.max_new
            skip = entry.delivered
        elif entry.stream is not None and entry.do_sample:
            # a sampled stream whose continuation exceeds the prefill
            # buckets cannot be replayed exactly-once: regenerating
            # would sample different tokens than the ones already
            # delivered — fail typed rather than break the stream.
            # Distinct audit code: this is NOT a budget problem, and
            # tuning FLAGS_gen_retry_limit can never fix it
            self.replay_impossible += 1
            eng._audit.audit("REPLAY_IMPOSSIBLE", rid=entry.rid,
                             generated=k, prompt_tokens=S,
                             bucket_max=bmax,
                             **({"trace": entry.trace_id}
                                if entry.trace_id else {}))
            self._fail_entry(entry, (
                f"{self.name}: sampled stream cannot be replayed "
                f"exactly-once (continuation of {S + k} tokens "
                f"exceeds the largest prefill bucket {bmax})"))
            return
        else:
            # from-scratch: greedy decode re-derives the identical
            # tokens, so a stream just suppresses re-delivery of the
            # first `delivered` ones
            prompt, max_new = entry.prompt, entry.max_new
            skip = entry.delivered
        try:
            eng.replay_submit(entry, prompt, max_new, skip_stream=skip)
            self.replayed += 1
        except UnavailableError:
            # the rebuilt engine ALREADY died (its death handler is
            # parked on the gate we hold) and this entry never landed
            # on it: defer to the next manifest with the retry budget
            # untouched — failing it here would charge a restart it
            # never got (the next handler drains _pending_replays on
            # every path, including breaker-open and shutdown)
            self._pending_replays.append(entry)
        except Exception as e:  # noqa: BLE001 — replay must fail typed,
            #                     never strand the caller
            self._fail_entry(entry,
                             f"{self.name}: replay failed: {e!r}")

    def _fail_entries(self, entries, msg: str) -> None:
        for entry in entries:
            self._fail_entry(entry, msg)

    def _fail_entry(self, entry: ReplayEntry, msg: str) -> None:
        err = UnavailableError(msg)
        if entry.stream is not None:
            entry.stream._put(err)
        try:
            entry.future.set_exception(err)
        except Exception:  # lint: allow(except-pass): racing caller-side cancel — the future is already settled
            pass
        slo.observe_request(self.name, ok=False)

    # -- submit surface -----------------------------------------------------

    def _current(self) -> GenerationEngine:
        with self._gate:
            if self._breaker_reason is not None:
                raise UnavailableError(
                    f"{self.name}: {self._breaker_reason}")
            if self._closed:
                raise UnavailableError(
                    f"{self.name}: supervisor is shut down")
            return self._engine

    def _await_recovery(self, eng: GenerationEngine) -> None:
        """Park until `eng` has been replaced or a final verdict
        (breaker open / shutdown) landed. A dying engine marks itself
        closed on its step thread BEFORE the death handler reaches the
        supervisor gate — a racing submit must wait for the swap here,
        not burn its retries against the corpse in that window. The
        park bound scales with the configured backoff ceiling: a
        legitimate slow recovery must not out-wait its waiters."""
        deadline = (time.monotonic() + 60.0
                    + self._backoff.max_delay_ms / 1000.0)
        with self._swap_cv:
            while (self._engine is eng and not self._closed
                   and self._breaker_reason is None
                   and time.monotonic() < deadline):
                self._swap_cv.wait(0.05)

    def _delegate(self, method: str, *args, **kw):
        # a submit can race a death: the engine raises "shut down",
        # _await_recovery parks until the restart lands, and the retry
        # goes to the new incarnation (bounded — not a loop)
        for attempt in range(3):
            eng = self._current()
            try:
                return getattr(eng, method)(*args, **kw)
            except UnavailableError:
                if attempt == 2:
                    raise
                self._await_recovery(eng)

    def submit(self, prompt_ids, **kw):
        """`GenerationEngine.submit` across restarts: the returned
        future survives engine deaths (replayed under the retry
        budget) — it fails only typed."""
        return self._delegate("submit", prompt_ids, **kw)

    def submit_stream(self, prompt_ids, **kw) -> TokenStream:
        """`GenerationEngine.submit_stream` across restarts: each token
        is delivered exactly once even when the engine dies and the
        sequence is replayed on the next incarnation."""
        return self._delegate("submit_stream", prompt_ids, **kw)

    def generate(self, prompt_ids, **kw) -> np.ndarray:
        return self.submit(prompt_ids, **kw).result()

    # -- introspection / lifecycle ------------------------------------------

    def supervisor_stats(self) -> dict:
        return {
            "incarnation": self.incarnation,
            "restarts": self.restarts,
            "replayed_requests": self.replayed,
            "retry_exhausted": self.retry_exhausted,
            "replay_impossible": self.replay_impossible,
            "retry_limit": self._retry_limit,
            "restarting": self._restarting,
            "last_recovery_ms": (round(self._last_recovery_ms, 3)
                                 if self._last_recovery_ms is not None
                                 else None),
            "replay_ms_total": round(self._replay_ms_total, 3),
            "breaker": self._breaker.state(),
            # warm start (ISSUE 16): whether a pack-less rebuild would
            # load from the on-disk store instead of recompiling
            "program_store": self._cfg.program_store,
        }

    def stats(self) -> dict:
        # gate NOT taken: /stats scrapes must not block behind a
        # restart (the dead engine's snapshot stays readable)
        eng = self._engine
        s = eng.stats()
        s["supervisor"] = self.supervisor_stats()
        return s

    def pressure(self) -> dict:
        """The live engine's `pressure()` snapshot (ISSUE 17) — gate
        NOT taken, same rationale as stats(): a router poll must never
        block behind a restart. Mid-restart the dead incarnation's last
        snapshot is returned; health() separately reports not-ready, so
        the router drains the replica rather than trusting the number."""
        return self._engine.pressure()

    def health(self) -> dict:
        """`/readyz` verdict across engine generations: breaker open →
        503 with the breaker reason; restarting → 503 "restarting";
        otherwise the live engine's own verdict."""
        if self._breaker_reason is not None:
            return {"ready": False, "reason": self._breaker_reason,
                    "breaker_open": True,
                    "incarnation": self.incarnation,
                    "restarts": self.restarts}
        if self._restarting:
            return {"ready": False,
                    "reason": "restarting (engine resurrection in "
                              "progress)",
                    "breaker_open": False,
                    "incarnation": self.incarnation,
                    "restarts": self.restarts}
        h = self._engine.health()
        h["incarnation"] = self.incarnation
        h["restarts"] = self.restarts
        h["breaker_open"] = False
        return h

    @property
    def engine(self) -> GenerationEngine:
        """The CURRENT engine incarnation (tests/benches; the object
        changes across restarts — don't cache it)."""
        return self._engine

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None):
        with self._gate:
            self._closed = True
            eng = self._engine
            pend, self._pending_replays = self._pending_replays, []
        # deferred replays whose next manifest never came (the engine
        # died mid-replay and we shut down before another death) must
        # not strand their callers
        self._fail_entries(pend, f"{self.name}: supervisor shut down")
        eng.shutdown(drain=drain, timeout_s=timeout_s)
        exporter.unregister_engine(self)
        if self._owns_metrics_server and self.metrics_server is not None:
            self.metrics_server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
