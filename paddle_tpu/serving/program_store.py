"""Keyed on-disk AOT executable store for the generation engine
(ISSUE 16): `_ProgramPack` survives a *process*.

PR 14 made the engine's jitted program set (`_ProgramPack`) survive a
supervised restart with zero new traces — but a new PROCESS still pays
the full trace+lower+compile bill for every (bucket, program) at
warmup, the autoscaling/fleet blocker ROADMAP names. This store
persists every covered program — per-bucket `prefill[b=S]` /
`prefill_tail[b=S]`, `decode[m=M]`, `verify[k=K]`, `cow_copy` — as a
serialized XLA executable under a CONTENT KEY, so a cold process whose
key matches warm-starts by deserializing instead of tracing.

Layout (one directory per key under the configured root):

    <root>/<key>/manifest.json       key material + per-program index
    <root>/<key>/<program>.bin       pickled (payload, in_tree, out_tree)

The key is `jit.key_material_digest` over everything that shapes the
traced programs: model config, the decode-weight pytree spec (shapes/
dtypes/paths — which IS the quant-manifest fingerprint: int8 leaves and
scale rows land there), the engine knobs that shape traces (slots,
page geometry, buckets, spec_k, top_k, tail/prefix wiring), jax/jaxlib
versions, backend + device kind, and the kernel-selection FLAGS the
programs bake in. Anything off by one bit → different key → clean miss,
never a wrong executable.

Trust model (the PR 1 lesson): a deserialized donated program is only
usable if its input/output aliasing survived the round trip. On a
backend where `device.serialization_unsafe_backend()` is True (XLA:CPU)
the store REFUSES to engage — the same single gate the persistent
compilation cache uses, so the two policies cannot drift — unless
forced, which emits the one-time corruption-class warning. Forced or
not, the ENGINE additionally runs a donation-aliasing self-check (the
loaded executable's alias spec vs the manifest's recorded
live-compiled spec) and a numeric smoke probe before any loaded
program enters the pack; failures dump a flight record and fall back
to live compile. Counters: STAT_pack_store_hits/_misses/_writes,
STAT_pack_selfcheck_failures, and the `pack_load_ms` histogram.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from ..framework import monitor

__all__ = ["ProgramStore", "read_manifest"]

_MANIFEST = "manifest.json"


def _safe_name(program: str) -> str:
    """`prefill[b=8]` → `prefill_b_8` — filesystem-safe, reversible
    enough for humans (the manifest keeps the exact program name)."""
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", program).strip("_")


def read_manifest(key_dir: str) -> Optional[dict]:
    """The key directory's manifest dict, or None when absent or
    unreadable (an unreadable manifest is a miss, never an error —
    the store must not be able to fail an engine start)."""
    path = os.path.join(key_dir, _MANIFEST)
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except Exception:
        return None


class ProgramStore:
    """One engine's view of the executable store: a resolved content
    key + load/store over that key's directory.

    All I/O is best-effort: a corrupt payload, a half-written file, or
    a permissions error degrades to a MISS (the engine live-compiles,
    exactly the store-off behavior) — the store can make a start
    faster, never wrong and never failed."""

    def __init__(self, root: str, key_material: dict, force: bool = False):
        from .. import device as _device
        from ..jit import key_material_digest
        self.root = os.path.expanduser(str(root))
        self.key = key_material_digest(key_material)
        self.key_dir = os.path.join(self.root, self.key)
        self._material = key_material
        # THE gate (shared with enable_compilation_cache): deserialized
        # executables on this backend drop donation aliasing — refuse
        # entirely unless forced, and never silently when forced
        self.refused = (_device.serialization_unsafe_backend()
                        and not force)
        if not self.refused and _device.serialization_unsafe_backend():
            _device.warn_forced_serialization(
                "ProgramStore(force=True)")
        self._hist = monitor.histogram("pack_load_ms")

    # -- read path ---------------------------------------------------------

    def load(self, program: str):
        """Deserialize `program` from this key's directory. Returns
        (compiled, recorded_alias_spec) on a hit, None on miss/refusal.
        The caller (engine warmup) owns the self-check + smoke probe —
        a returned executable is NOT yet trusted."""
        if self.refused:
            return None
        mf = read_manifest(self.key_dir)
        entry = (mf or {}).get("programs", {}).get(program)
        if entry is None:
            monitor.stat_add("STAT_pack_store_misses")
            return None
        t0 = time.perf_counter()
        try:
            from ..jit import deserialize_compiled
            with open(os.path.join(self.key_dir, entry["file"]),
                      "rb") as f:
                blob = f.read()
            compiled = deserialize_compiled(blob)
        except Exception:
            # corrupt/truncated payload: a miss, not an error — the
            # engine live-compiles and the next store() overwrites
            monitor.stat_add("STAT_pack_store_misses")
            return None
        self._hist.observe((time.perf_counter() - t0) * 1000.0)
        monitor.stat_add("STAT_pack_store_hits")
        return compiled, str(entry.get("alias", ""))

    # -- write path --------------------------------------------------------

    def store(self, program: str, compiled) -> bool:
        """Serialize a live-compiled executable under `program`,
        recording its alias spec (the live compile's ground truth the
        next process self-checks against). Atomic per file
        (tmp+rename); the manifest is rewritten last so a reader never
        sees an indexed-but-absent payload. Returns True on success."""
        if self.refused:
            return False
        try:
            from ..jit import compiled_alias_spec, serialize_compiled
            blob = serialize_compiled(compiled)
            alias = compiled_alias_spec(compiled)
            os.makedirs(self.key_dir, exist_ok=True)
            fname = _safe_name(program) + ".bin"
            tmp = os.path.join(self.key_dir,
                               f".{fname}.tmp.{os.getpid()}")
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(self.key_dir, fname))
            mf = read_manifest(self.key_dir) or self._fresh_manifest()
            mf.setdefault("programs", {})[program] = {
                "file": fname, "bytes": len(blob), "alias": alias}
            tmp = os.path.join(self.key_dir,
                               f".{_MANIFEST}.tmp.{os.getpid()}")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(mf, f, indent=1, sort_keys=True)
            os.replace(tmp, os.path.join(self.key_dir, _MANIFEST))
        except Exception:
            return False
        monitor.stat_add("STAT_pack_store_writes")
        return True

    def _fresh_manifest(self) -> dict:
        import jax
        import jaxlib
        dev = jax.devices()[0]
        return {
            "key": self.key,
            "key_material": self._material,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", "unknown"),
            "programs": {},
        }

    # -- introspection (tools/pack_inspect.py) -----------------------------

    def entries(self) -> dict:
        """{program: {file, bytes, alias}} for this key (may be {})."""
        mf = read_manifest(self.key_dir)
        return dict((mf or {}).get("programs", {}))
