"""Paged KV cache: block allocator + preallocated per-layer K/V pools.

vLLM's PagedAttention memory model on TPU terms: decode-time K/V for
every live sequence lives in ONE pair of preallocated pools
`[L, H, num_pages, page_size, D]`, carved into fixed-size pages handed
out by a free-list allocator. A sequence owns `ceil(tokens / page_size)`
pages recorded in a fixed-width page-table row (trash-padded), so the
device-side shapes never depend on how many sequences are live or how
long they are — the prerequisite for the generation engine's single
compiled decode step.

Design points:

- **Page 0 is reserved scratch ("trash")**: inactive decode slots and
  padded prefill tails write there, and page-table padding points there,
  so masked lanes always have a legal physical target. It is never
  allocated.
- **Worst-case admission**: `can_admit(tokens)` is exact page
  arithmetic over the request's prompt + max-new budget; the engine
  refuses admission (keeps the request queued) while free pages are
  short, so a mid-decode sequence can never be starved of the pages it
  was promised — no mid-flight OOM, evictions only on deadline/poison.
- **Zero-on-free**: freed pages are zeroed by the owner engine before
  reuse (`zero_rows` builds the scatter coordinates). Masked attention
  multiplies stale entries by exactly 0.0, which is only safe when
  stale never means NaN/Inf — a poisoned sequence's pages must not
  leak NaNs into the next owner's masked lanes (0.0 * NaN = NaN).
- **Refcounted sharing (prefix cache, ISSUE 12)**: every allocated page
  carries a refcount. `alloc_shared` maps an already-filled prefix
  chain read-only into a new sequence's page table (incref), the
  prefix index itself holds a reference on registered pages
  (`cache_hold`), and `cow_split` swaps one shared page for a private
  copy. Zero-on-free now keys on refcounts, not ownership: `free()`
  returns ONLY the pages whose count hit 0 — a page another sequence
  (or the prefix index) still reads is never zeroed under it. Pages
  held only by the index (`refcount == 1` and cache-held) are
  *evictable*: `can_admit`/`headroom` count them as reclaimable so
  admission capacity stays truthful, and the engine evicts them (LRU,
  via the prefix index) before allocating.
- Host-side state is plain python under the engine's lock; the pools
  themselves are jnp arrays the engine threads through its jitted
  step functions (donated, so XLA updates them in place).
- **int8 page mode** (`dtype="int8"`, FLAGS_kv_cache_dtype): pools
  store int8 with parallel per-(layer, head, page) fp32 scale pools
  (`k_scales`/`v_scales`); `ops/paged_ops.paged_write_quantized`
  quantizes on append, the attention path dequantizes on gather. One
  page costs ~4x fewer HBM bytes than fp32
  (`page_hbm_bytes`/`pages_for_budget` do the arithmetic), so the same
  pool budget admits ~4x the concurrent sequences — the quantized-
  serving capacity multiplier. Zero-on-free covers the scale pools:
  a freed page's scale resets to 0 ("empty") with its content.
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Optional

import numpy as np

from ..framework import monitor
from ..framework.errors import InvalidArgumentError, ResourceExhaustedError

__all__ = ["PagedKVCache"]

TRASH_PAGE = 0

# STAT_kv_cache_hbm_bytes gauges pool bytes across LIVE caches: each
# cache gauge_add()s its pool (+ scale-pool) bytes at construction and
# subtracts them when collected (weakref.finalize — the engine drops
# its cache on GC, there is no explicit close), so a multi-engine
# process exports the aggregate of what actually exists rather than
# whichever pool was built last.
def _note_pool_bytes(delta: int) -> None:
    monitor.stat_gauge_add("STAT_kv_cache_hbm_bytes", delta)


# Per-shard companion gauge (ISSUE 19): on a tp mesh each device holds
# heads/tp of every pool, so the PER-DEVICE HBM cost is total/tp — the
# number admission headroom and capacity planning must use. Only
# tp>1 caches contribute; shard gauges times tp reconcile with the
# aggregate STAT_kv_cache_hbm_bytes for those caches.
def _note_shard_bytes(delta: int) -> None:
    monitor.stat_gauge_add("STAT_tp_kv_shard_bytes", delta)


class PagedKVCache:
    """Block allocator over per-layer paged K/V pools.

    `alloc()`/`free()` are NOT thread-safe — the generation engine calls
    them from its single step thread (same single-writer discipline as
    the PR 3 collector)."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 page_size: int, num_pages: int, pages_per_seq: int,
                 dtype="float32", mesh=None, tp_axis: str = "tp"):
        if page_size < 1 or num_pages < 2 or pages_per_seq < 1:
            raise InvalidArgumentError(
                f"PagedKVCache needs page_size>=1, num_pages>=2 (page 0 "
                f"is reserved scratch), pages_per_seq>=1; got "
                f"{page_size}/{num_pages}/{pages_per_seq}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.pages_per_seq = int(pages_per_seq)
        self.dtype = str(dtype)
        self.quantized = self.dtype == "int8"
        # mesh-sliced pools (ISSUE 19): on a tp mesh the K/V pools (and
        # the int8 scale grids) are laid out head-sharded with
        # NamedSharding — each device holds [L, H/tp, N, P, D], so one
        # chip's HBM pays total/tp and the page axis stays FULL on every
        # shard (page ids, tables and the allocator are tp-invariant)
        self.mesh = mesh
        self.tp_axis = str(tp_axis)
        self.tp = int(mesh.shape[tp_axis]) if mesh is not None else 1
        if self.num_heads % self.tp != 0:
            raise InvalidArgumentError(
                f"num_heads={self.num_heads} not divisible by "
                f"tp={self.tp} — head-sharded pools need equal slices")
        import jax.numpy as jnp
        shape = (self.num_layers, self.num_heads, self.num_pages,
                 self.page_size, self.head_dim)
        self.k_pages = self._place(jnp.zeros(shape, self.dtype))
        self.v_pages = self._place(jnp.zeros(shape, self.dtype))
        # int8 page mode: per-(layer, head, page) symmetric abs-max
        # scales in a parallel pool (dequant = q * scale; scale 0 means
        # "page empty" — zero-on-free resets both pools, so a freed
        # page's next owner starts from a clean quantization grid)
        if self.quantized:
            sshape = (self.num_layers, self.num_heads, self.num_pages)
            self.k_scales = self._place(jnp.zeros(sshape, "float32"))
            self.v_scales = self._place(jnp.zeros(sshape, "float32"))
        else:
            self.k_scales = self.v_scales = None
        # LIFO free list: the page freed last is reallocated first, so a
        # hot pool keeps touching the same HBM region
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._owned: Dict[int, List[int]] = {}  # seq id -> pages
        self._ref: Dict[int, int] = {}          # page -> refcount
        # pages the prefix index holds a reference on (cache_hold);
        # evictable = cache-held AND refcount 1 (no live sequence reads)
        self._cache_held: set = set()
        # free-list watermarks since construction: the low-water mark is
        # "how close did this pool ever get to exhaustion" — the
        # capacity-planning number /stats surfaces (ISSUE 11)
        self._free_low_water = len(self._free)
        self._free_high_water = len(self._free)
        monitor.stat_set("STAT_kv_pages_inuse", 0)
        b = self.hbm_bytes()
        _note_pool_bytes(b)
        weakref.finalize(self, _note_pool_bytes, -b)
        if self.tp > 1:
            s = self.shard_hbm_bytes()
            _note_shard_bytes(s)
            weakref.finalize(self, _note_shard_bytes, -s)

    def _place(self, arr):
        """Lay one pool onto the tp mesh head-sharded (axis 1); a
        mesh-less cache keeps the single-device default placement."""
        if self.mesh is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        spec = [None] * arr.ndim
        spec[1] = self.tp_axis
        return jax.device_put(
            arr, NamedSharding(self.mesh, PartitionSpec(*spec)))

    # -- capacity arithmetic ----------------------------------------------

    @staticmethod
    def page_hbm_bytes(num_layers: int, num_heads: int, head_dim: int,
                       page_size: int, dtype="float32", tp: int = 1) -> int:
        """Device bytes ONE page costs across both pools (K and V, every
        layer), including its slice of the int8 scale pools — the unit
        of the capacity arithmetic below. With `tp > 1` this is the
        PER-SHARD cost (each device stores heads/tp of the page), the
        number a per-chip HBM budget actually pays — router pressure
        and `pages_for_budget` must size against the shard, not the
        unsharded fiction."""
        tp = int(tp)
        if tp < 1 or num_heads % tp != 0:
            raise InvalidArgumentError(
                f"num_heads={num_heads} not divisible by tp={tp}")
        item = np.dtype(dtype).itemsize
        hl = num_heads // tp
        b = 2 * num_layers * hl * page_size * head_dim * item
        if str(dtype) == "int8":
            b += 2 * num_layers * hl * 4  # fp32 scale per (L, H/tp)
        return b

    def page_host_bytes(self) -> int:
        """Host-RAM bytes ONE page costs demoted into the kv_tier
        store: the raw K/V page blocks in the pool dtype plus (int8
        mode) the fp32 scale rows — identical arithmetic to
        `page_hbm_bytes`, because the tier stores the bytes RAW (no
        transcoding; that is the cross-tier exactness guarantee). The
        tier byte-budget / working-set sizing unit (ISSUE 18). Always
        the FULL (unsharded) page: the tier gather reassembles every
        head shard into one host block, so host RAM pays tp-invariant
        bytes per page."""
        return self.page_hbm_bytes(self.num_layers, self.num_heads,
                                   self.head_dim, self.page_size,
                                   self.dtype)

    @classmethod
    def pages_for_budget(cls, budget_bytes: int, *, num_layers: int,
                         num_heads: int, head_dim: int, page_size: int,
                         dtype="float32", tp: int = 1) -> int:
        """Most pages (incl. the reserved scratch page) an HBM budget
        admits: int8 pages are ~4x denser than fp32 — the serving-
        capacity multiplier the quantized KV mode exists for, and how
        bench.py builds equal-byte fp32/int8 pools. `budget_bytes` is
        PER-CHIP HBM; with tp > 1 each chip stores only heads/tp of
        every page, so the same per-chip budget admits tp× the pages —
        the mesh-slice capacity unlock (ISSUE 19)."""
        per = cls.page_hbm_bytes(num_layers, num_heads, head_dim,
                                 page_size, dtype, tp=tp)
        return max(2, int(budget_bytes) // per)

    def hbm_bytes(self) -> int:
        """Live device bytes of the K/V pools + scale pools (summed
        across every shard on a tp mesh)."""
        b = int(self.k_pages.nbytes) + int(self.v_pages.nbytes)
        if self.quantized:
            b += int(self.k_scales.nbytes) + int(self.v_scales.nbytes)
        return b

    def shard_hbm_bytes(self) -> int:
        """Per-device pool bytes: heads shard evenly over tp, so ONE
        chip's HBM holds exactly total/tp — the gauge admission headroom
        reasons about (shards × tp reconcile to `hbm_bytes`)."""
        return self.hbm_bytes() // self.tp

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # minus the trash page

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.usable_pages - len(self._free)

    @property
    def max_tokens_per_seq(self) -> int:
        return self.pages_per_seq * self.page_size

    def pages_needed(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size)  # ceil

    def fits(self, tokens: int) -> bool:
        """Could `tokens` EVER be admitted (table width + pool size)?"""
        need = self.pages_needed(tokens)
        return need <= self.pages_per_seq and need <= self.usable_pages

    @property
    def evictable_pages(self) -> int:
        """Pages the prefix index alone holds (refcount 1, cache-held):
        reclaimable on demand by an LRU eviction before alloc."""
        return sum(1 for p in list(self._cache_held)
                   if self._ref.get(p) == 1)

    @property
    def reclaimable_pages(self) -> int:
        """Free-list pages plus evictable cached pages — the honest
        admission capacity (ISSUE 12: cached-but-evictable counts as
        free, with the eviction performed before alloc)."""
        return len(self._free) + self.evictable_pages

    def can_admit(self, tokens: int) -> bool:
        """Admission check: worst-case pages available RIGHT NOW (free
        list + evictable cached pages — the caller evicts before
        alloc)."""
        need = self.pages_needed(tokens)
        return need <= self.pages_per_seq and need <= self.reclaimable_pages

    # -- alloc / free ------------------------------------------------------

    def alloc(self, seq_id: int, tokens: int) -> np.ndarray:
        """Reserve worst-case pages for `tokens`; returns the sequence's
        fixed-width page-table row (trash-padded int32 [pages_per_seq]).
        Raises ResourceExhaustedError when the pool is short — callers
        gate on `can_admit` so this raising means an accounting bug."""
        if seq_id in self._owned:
            raise InvalidArgumentError(
                f"sequence {seq_id} already holds pages")
        need = self.pages_needed(tokens)
        if need > self.pages_per_seq:
            raise InvalidArgumentError(
                f"{tokens} tokens need {need} pages > pages_per_seq="
                f"{self.pages_per_seq} (page_size={self.page_size})")
        if need > len(self._free):
            raise ResourceExhaustedError(
                f"KV page pool exhausted: need {need} pages, "
                f"{len(self._free)} free of {self.usable_pages}")
        pages = [self._free.pop() for _ in range(need)]
        for p in pages:
            self._ref[p] = 1
        self._owned[seq_id] = pages
        self._free_low_water = min(self._free_low_water, len(self._free))
        monitor.stat_set("STAT_kv_pages_inuse", self.pages_in_use)
        row = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
        row[:need] = pages
        return row

    def alloc_shared(self, seq_id: int, tokens: int,
                     shared_pages: List[int]) -> np.ndarray:
        """Like `alloc`, but the leading pages of the page-table row map
        an already-filled prefix chain READ-ONLY (each shared page's
        refcount is incremented; the sequence never writes them — its
        first write position sits past the shared prefix, or behind a
        `cow_split`). Only the tail pages come off the free list."""
        if seq_id in self._owned:
            raise InvalidArgumentError(
                f"sequence {seq_id} already holds pages")
        need = self.pages_needed(tokens)
        fresh = need - len(shared_pages)
        if fresh < 0 or need > self.pages_per_seq:
            raise InvalidArgumentError(
                f"{tokens} tokens need {need} pages "
                f"(pages_per_seq={self.pages_per_seq}, "
                f"{len(shared_pages)} shared)")
        for p in shared_pages:
            if self._ref.get(p, 0) < 1:
                raise InvalidArgumentError(
                    f"shared page {p} is not allocated")
        if fresh > len(self._free):
            raise ResourceExhaustedError(
                f"KV page pool exhausted: need {fresh} fresh pages, "
                f"{len(self._free)} free of {self.usable_pages}")
        for p in shared_pages:
            self._ref[p] += 1
        pages = [self._free.pop() for _ in range(fresh)]
        for p in pages:
            self._ref[p] = 1
        self._owned[seq_id] = list(shared_pages) + pages
        self._free_low_water = min(self._free_low_water, len(self._free))
        monitor.stat_set("STAT_kv_pages_inuse", self.pages_in_use)
        row = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
        row[:need] = self._owned[seq_id]
        return row

    def _decref(self, page: int) -> bool:
        """Drop one reference; True when the page actually returned to
        the free list (refcount hit 0) — zero-on-free applies to
        exactly these pages and DEFERS while any sharer remains."""
        n = self._ref.get(page, 0) - 1
        if n > 0:
            self._ref[page] = n
            return False
        self._ref.pop(page, None)
        self._cache_held.discard(page)
        self._free.append(page)
        return True

    def free(self, seq_id: int) -> List[int]:
        """Release a sequence's references; returns ONLY the pages whose
        refcount hit 0 (the engine zeroes those on device before reuse
        — pages another sequence or the prefix index still reads are
        NOT returned and must not be zeroed). Idempotent — a double
        free (evict racing natural EOS) is a no-op."""
        pages = self._owned.pop(seq_id, [])
        freed = [p for p in pages if self._decref(p)]
        self._free_high_water = max(self._free_high_water,
                                    len(self._free))
        monitor.stat_set("STAT_kv_pages_inuse", self.pages_in_use)
        return freed

    # -- prefix-cache references (ISSUE 12) --------------------------------

    def pin(self, pages: List[int]) -> None:
        """Temporarily incref pages (an admission holding its matched
        chain across an eviction pass); pair with `unpin`."""
        for p in pages:
            if self._ref.get(p, 0) < 1:
                raise InvalidArgumentError(f"page {p} is not allocated")
            self._ref[p] += 1

    def unpin(self, pages: List[int]) -> List[int]:
        """Drop a `pin`; returns any pages freed (refcount hit 0)."""
        freed = [p for p in pages if self._decref(p)]
        if freed:
            self._free_high_water = max(self._free_high_water,
                                        len(self._free))
            monitor.stat_set("STAT_kv_pages_inuse", self.pages_in_use)
        return freed

    def cache_hold(self, pages: List[int]) -> None:
        """The prefix index takes a reference on registered chain pages:
        they survive their producer sequence's free (content preserved
        for future hits) and become evictable once no live sequence
        shares them."""
        self.pin(pages)
        self._cache_held.update(pages)

    def cache_release(self, pages: List[int]) -> List[int]:
        """Drop the prefix index's reference (chain eviction); returns
        the pages freed NOW (refcount 0 → caller zeroes them). Pages a
        live sequence still shares stay allocated and zero later, when
        that sequence frees."""
        for p in pages:
            self._cache_held.discard(p)
        return self.unpin(pages)

    def cow_split(self, seq_id: int, old_page: int) -> int:
        """Copy-on-write split: swap one SHARED page in `seq_id`'s
        ownership for a fresh private page (the caller copies content —
        and the int8 scale row — on device, then writes through the
        private copy). Returns the new page id; the shared original
        keeps its other readers."""
        pages = self._owned.get(seq_id)
        if pages is None or old_page not in pages:
            raise InvalidArgumentError(
                f"sequence {seq_id} does not hold page {old_page}")
        if self._ref.get(old_page, 0) < 2:
            raise InvalidArgumentError(
                f"page {old_page} is not shared (refcount "
                f"{self._ref.get(old_page, 0)}); split is pointless")
        if not self._free:
            raise ResourceExhaustedError(
                "KV page pool exhausted: no free page for CoW split")
        new = self._free.pop()
        self._ref[new] = 1
        self._ref[old_page] -= 1
        pages[pages.index(old_page)] = new
        self._free_low_water = min(self._free_low_water, len(self._free))
        monitor.stat_set("STAT_kv_pages_inuse", self.pages_in_use)
        return new

    def refcounts(self) -> Dict[int, int]:
        """{page: refcount} snapshot (per-key atomic gets, same scraper
        contract as owners())."""
        out = {}
        for p in list(self._ref):
            n = self._ref.get(p)
            if n is not None:
                out[p] = n
        return out

    def cached_pages(self) -> List[int]:
        """Pages the prefix index currently holds (snapshot)."""
        return list(self._cache_held)

    def owned(self, seq_id: int) -> Optional[List[int]]:
        pages = self._owned.get(seq_id)
        return list(pages) if pages is not None else None

    def owners(self) -> Dict[int, List[int]]:
        """Page-ownership map `{seq_id: [page, ...]}` — which physical
        pages each live sequence holds (KV-pool introspection; the
        engine joins it against its slot table for `stats()["kv"]`).

        Read from scraper threads while the step thread allocs/frees:
        iterate a key snapshot + per-key atomic gets (each a single
        GIL-atomic dict op) instead of `.items()`, which would raise
        `dictionary changed size during iteration` mid-scrape. A page
        list never changes SIZE after alloc (cow_split swaps one item
        in place, a GIL-atomic store), so copying it is safe."""
        out = {}
        for sid in list(self._owned):
            pages = self._owned.get(sid)
            if pages is not None:
                out[sid] = list(pages)
        return out

    def headroom(self, token_counts) -> Dict[int, int]:
        """Admission-headroom estimate: for each representative request
        size (total tokens = prompt + max_new), how many MORE such
        requests `can_admit` would accept RIGHT NOW from the free list
        plus the evictable cached pages (0 when the shape can never fit
        the page table) — evictable pages ARE admission capacity (the
        engine evicts before alloc), so the router-pressure surface
        must not under-report them (ISSUE 12). The router tier
        compares this across replicas to place work."""
        out = {}
        free = self.reclaimable_pages
        for tokens in token_counts:
            need = self.pages_needed(tokens)
            if need > self.pages_per_seq or need <= 0:
                out[int(tokens)] = 0
            else:
                out[int(tokens)] = free // need
        return out

    def zero_rows(self, pages: List[int]) -> np.ndarray:
        """Fixed-width page-id row for the engine's jitted zeroing
        scatter (trash-padded so one compiled shape serves every free)."""
        row = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
        row[:len(pages)] = pages[:self.pages_per_seq]
        return row

    def stats(self) -> dict:
        return {
            "dtype": self.dtype,
            "quantized": self.quantized,
            "hbm_bytes": self.hbm_bytes(),
            # mesh-slice lanes (ISSUE 19): per-device pool bytes — what
            # ONE chip's HBM actually pays (== hbm_bytes when tp == 1)
            "tp": self.tp,
            "shard_hbm_bytes": self.shard_hbm_bytes(),
            "page_size": self.page_size,
            "usable_pages": self.usable_pages,
            "pages_in_use": self.pages_in_use,
            "free_pages": self.free_pages,
            "pages_per_seq": self.pages_per_seq,
            "sequences": len(self._owned),
            "occupancy": round(self.pages_in_use
                               / max(1, self.usable_pages), 4),
            "free_low_water": self._free_low_water,
            "free_high_water": self._free_high_water,
            # prefix-cache occupancy (ISSUE 12): cached = held by the
            # prefix index at all; evictable = held ONLY by it —
            # reclaimable is the truthful admission capacity
            "cached_pages": len(self._cache_held),
            "evictable_pages": self.evictable_pages,
            "reclaimable_pages": self.reclaimable_pages,
        }

    def manifest(self) -> dict:
        """Crash-manifest snapshot (ISSUE 15): pool stats plus the
        ownership and refcount maps, captured at engine death so the
        flight dump records exactly which sequences held which pages
        when the pools were lost — the rebuilt engine starts from a
        FRESH pool, so this is the only record of the dead layout."""
        return {"stats": self.stats(), "owners": self.owners(),
                "refcounts": self.refcounts()}
