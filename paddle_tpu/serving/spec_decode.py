"""Model-free draft proposal for speculative decoding (ISSUE 14).

Decode is weight-streaming-bound: one engine step reads every weight
byte to advance each sequence ONE token. In that regime a single
forward pass over k+1 positions costs barely more than one position —
so if something cheap can GUESS the next k tokens, the verify program
(`serving/generation.py`, built on the `gpt_spec_verify` seam) scores
all k guesses plus the bonus position in one pass, the engine keeps
the longest agreeing prefix, and accepted steps deliver up to k+1
tokens for one weight stream.

The proposer here is **prompt lookup** (n-gram continuation): the next
tokens of a sequence are guessed from the sequence's OWN history —
find the most recent earlier occurrence of the trailing n-gram and
propose the tokens that followed it. No second model, no device work,
no extra weights: pure numpy over the host-side token list, which is
what makes the whole speculative path CPU-testable and keeps the draft
cost invisible next to the verify dispatch. It shines exactly where
production decode spends its tokens — code, quoting, JSON, multi-turn
agent loops, and the repetition attractors of greedy decoding — and
degrades to plain one-token-per-step decode when nothing matches
(a miss costs only masked verify lanes, never a wrong token:
acceptance is exact greedy agreement, so engine output is
token-identical with speculation on or off).

Proposal is per-slot and stateless across steps; the verify program
and the acceptance bookkeeping live in the engine (single writer, its
step thread). `FLAGS_gen_spec_k` sizes the draft block,
`FLAGS_gen_spec_ngram` the longest pattern tried.
"""
from __future__ import annotations

import numpy as np

from ..framework.errors import InvalidArgumentError

__all__ = ["NGramProposer"]


class NGramProposer:
    """Prompt-lookup draft proposer: continue the trailing n-gram from
    its most recent earlier occurrence in the sequence's own tokens.

    Tries pattern lengths `max_ngram` down to 1 (longer matches are
    stronger evidence); within one length the RIGHTMOST earlier
    occurrence **with k following tokens** wins — recent context beats
    distant context (the locality assumption of prompt lookup), but a
    match flush against the end of the history can only propose the
    few tokens after it, which on a periodic tail (exactly where
    lookup shines) would cap every proposal at one token; preferring
    the nearest match that can fund a FULL draft block keeps the
    proposal k long while staying as recent as possible. When no
    occurrence has k followers the plain rightmost wins (partial
    proposal). Returns at most `k` draft tokens; an empty proposal
    means "no signal", and the engine runs that slot as plain
    one-token decode inside the same verify program (its draft lanes
    masked)."""

    def __init__(self, max_ngram: int = 3):
        if int(max_ngram) < 1:
            raise InvalidArgumentError(
                f"NGramProposer needs max_ngram >= 1, got {max_ngram}")
        self.max_ngram = int(max_ngram)

    def propose(self, tokens: np.ndarray, k: int) -> np.ndarray:
        """Up to `k` draft tokens continuing `tokens` (1-D int array:
        the sequence's prompt + generated tokens so far). Empty when
        the history carries no matching n-gram."""
        toks = np.asarray(tokens, np.int32)
        T = int(toks.size)
        k = int(k)
        if k <= 0 or T < 2:
            return np.zeros((0,), np.int32)
        for n in range(min(self.max_ngram, T - 1), 0, -1):
            pat = toks[T - n:]
            # candidate starts s < T - n: the trailing pattern itself is
            # excluded, and every candidate has >= 1 following token
            windows = np.lib.stride_tricks.sliding_window_view(
                toks[:T - 1], n)                    # [T-n, n]
            hits = np.flatnonzero((windows == pat[None]).all(axis=1))
            if hits.size == 0:
                continue
            full = hits[hits + n + k <= T]          # can fund k drafts
            s = int(full[-1] if full.size else hits[-1])
            out = toks[s + n:s + n + k]
            if out.size:
                return out.astype(np.int32, copy=True)
        return np.zeros((0,), np.int32)
