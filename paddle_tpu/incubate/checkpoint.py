"""Auto-checkpoint (reference
`fluid/incubate/checkpoint/auto_checkpoint.py:265` TrainEpochRange /
`:598` train_epoch_range / `:71` AutoCheckpointChecker): epoch-scoped
save/restore keyed by job id — restart resumes from the last epoch."""
from __future__ import annotations

import json
import os
from typing import Optional

__all__ = ["AutoCheckpointChecker", "TrainEpochRange", "train_epoch_range"]


class AutoCheckpointChecker:
    """env contract (reference :71): PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT,
    PADDLE_JOB_ID, PADDLE_EDL_HDFS_CHECKPOINT_PATH (any fs path here)."""

    def __init__(self):
        self.run_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "default_job")
        self.ckpt_path = os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH",
                                        os.environ.get(
                                            "PADDLE_CHECKPOINT_PATH",
                                            "./auto_ckpt"))
        self.save_interval = int(os.environ.get(
            "PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900"))

    def get_job_checkpoint_path(self):
        return os.path.join(self.ckpt_path, self.job_id)

    @property
    def valid(self):
        return self.run_env == "PADDLE_EDL_AUTO_CHECKPOINT" or \
            os.environ.get("PADDLE_AUTO_CHECKPOINT", "") == "1"


class TrainEpochRange:
    """Iterate epochs; on construction restores the last finished epoch's
    model state; after each epoch saves model+meta atomically."""

    def __init__(self, max_epoch_num, name, checkpoint_inter=None,
                 save_checkpoint=True):
        self.max_epoch_num = max_epoch_num
        self.name = name
        self.checker = AutoCheckpointChecker()
        self.save_checkpoint = save_checkpoint
        self._models = []
        self._start_epoch = 0
        self._dir = os.path.join(self.checker.get_job_checkpoint_path(),
                                 name)
        self._meta_path = os.path.join(self._dir, "meta.json")
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                self._start_epoch = json.load(f).get("epoch", -1) + 1

    def add(self, layer, optimizer=None):
        """Register a Layer (+optimizer) whose state rides the checkpoint."""
        self._models.append((layer, optimizer))
        if self._start_epoch > 0:
            self._restore()
        return self

    def _restore(self):
        from ..framework.io_state import load
        for i, (layer, opt) in enumerate(self._models):
            p = os.path.join(self._dir, f"model_{i}.pdparams")
            if os.path.exists(p):
                layer.set_state_dict(load(p))
            if opt is not None:
                po = os.path.join(self._dir, f"model_{i}.pdopt")
                if os.path.exists(po):
                    opt.set_state_dict(load(po))

    def _save(self, epoch):
        from ..framework.io_state import save
        os.makedirs(self._dir, exist_ok=True)
        for i, (layer, opt) in enumerate(self._models):
            save(layer.state_dict(),
                 os.path.join(self._dir, f"model_{i}.pdparams"))
            if opt is not None:
                save(opt.state_dict(),
                     os.path.join(self._dir, f"model_{i}.pdopt"))
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "name": self.name}, f)
        os.replace(tmp, self._meta_path)

    def get(self):
        return self._start_epoch

    def __iter__(self):
        for epoch in range(self._start_epoch, self.max_epoch_num):
            yield epoch
            if self.save_checkpoint:
                self._save(epoch)


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None):
    return TrainEpochRange(max_epoch_num, "_range_",
                           save_checkpoint_inter)
