"""paddle.incubate.autotune (parity shim — XLA autotunes its own tilings;
exposed so reference code calling set_config keeps working)."""
from __future__ import annotations

__all__ = ["set_config"]

_config = {}


def set_config(config=None):
    if config:
        _config.update(config)
    return dict(_config)
