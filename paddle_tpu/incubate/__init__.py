"""paddle.incubate (reference `python/paddle/incubate/`): LookAhead,
ModelAverage, GradientMerge, auto-checkpoint."""
from . import autotune  # noqa: F401
from .checkpoint import (AutoCheckpointChecker, TrainEpochRange,
                         train_epoch_range)
from .optimizers import (GradientMergeOptimizer, LookAhead, LookaheadOptimizer,
                         ModelAverage, RecomputeOptimizer)

__all__ = ["LookAhead", "LookaheadOptimizer", "ModelAverage",
           "GradientMergeOptimizer", "RecomputeOptimizer",
           "TrainEpochRange", "train_epoch_range", "AutoCheckpointChecker"]
