"""paddle.incubate (reference `python/paddle/incubate/`): LookAhead,
ModelAverage, GradientMerge, auto-checkpoint."""
from . import autotune  # noqa: F401
from .checkpoint import (AutoCheckpointChecker, TrainEpochRange,
                         train_epoch_range)
from .optimizers import (GradientMergeOptimizer, LookAhead, LookaheadOptimizer,
                         ModelAverage, RecomputeOptimizer)

__all__ = ["LookAhead", "LookaheadOptimizer", "ModelAverage",
           "GradientMergeOptimizer", "RecomputeOptimizer",
           "TrainEpochRange", "train_epoch_range", "AutoCheckpointChecker"]

from ..ops.extra_ops import (segment_max, segment_mean,  # noqa: F401,E402
                             segment_min, segment_sum)

__all__ += ["segment_sum", "segment_mean", "segment_max", "segment_min"]
