"""Wrapper optimizers (reference `fluid/optimizer.py`:
LookaheadOptimizer:5230, GradientMergeOptimizer:5402,
RecomputeOptimizer:4549; `incubate/optimizer/modelaverage.py`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "LookaheadOptimizer", "ModelAverage",
           "GradientMergeOptimizer", "RecomputeOptimizer"]


class LookAhead(Optimizer):
    """slow weights track fast weights every k steps."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._steps = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self):
        self.inner.step()
        self._steps += 1
        if self._steps % self.k:
            return
        for p in (self.inner._parameter_list or []):
            key = id(p)
            if key not in self._slow:
                self._slow[key] = p._value
            slow = self._slow[key] + self.alpha * (p._value -
                                                   self._slow[key])
            self._slow[key] = slow
            p._value = slow

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        self.inner.clear_grad()


LookaheadOptimizer = LookAhead


class ModelAverage(Optimizer):
    """EMA of parameters with apply/restore (reference
    `incubate/optimizer/modelaverage.py`)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(0.0, parameters)
        self._sums = {}
        self._counts = {}
        self._backup = {}

    def step(self):
        for p in (self._parameter_list or []):
            k = id(p)
            self._sums[k] = self._sums.get(k, 0) + p._value
            self._counts[k] = self._counts.get(k, 0) + 1

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            for p in (self._parameter_list or []):
                k = id(p)
                if k in self._sums and self._counts.get(k):
                    self._backup[k] = p._value
                    p._value = self._sums[k] / self._counts[k]
            try:
                yield
            finally:
                if need_restore:
                    self.restore()
        return ctx()

    def restore(self, executor=None):
        for p in (self._parameter_list or []):
            k = id(p)
            if k in self._backup:
                p._value = self._backup.pop(k)


class GradientMergeOptimizer:
    """Accumulate grads for k steps, then apply (reference
    `fluid/optimizer.py:5402` + gradient_merge meta-optimizer)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner = inner_optimizer
        self.k_steps = k_steps
        self.avg = avg
        self._acc = {}
        self._count = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self):
        self._count += 1
        for p in (self.inner._parameter_list or []):
            if p._grad is None:
                continue
            k = id(p)
            self._acc[k] = self._acc.get(k, 0) + p._grad
            p._grad = None
        if self._count < self.k_steps:
            return
        for p in (self.inner._parameter_list or []):
            k = id(p)
            if k in self._acc:
                g = self._acc[k]
                p._grad = g / self.k_steps if self.avg else g
        self.inner.step()
        self.inner.clear_grad()
        self._acc = {}
        self._count = 0

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        self.inner.clear_grad()


class RecomputeOptimizer:
    """reference `fluid/optimizer.py:4549`. In this framework recompute is
    a jit-level policy (jax.checkpoint in the SPMD step builder /
    strategy.recompute); this wrapper exists for API compat and simply
    forwards — eager mode has no stored activations to drop because the
    tape stores vjp residuals XLA chose."""

    def __init__(self, optimizer):
        self.inner = optimizer

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def step(self):
        self.inner.step()

    def minimize(self, loss, **kw):
        return self.inner.minimize(loss, **kw)
