"""AMP (reference `python/paddle/amp/auto_cast.py`, `grad_scaler.py`;
static lists `fluid/contrib/mixed_precision/fp16_lists.py:20`).

TPU-native: level O1 autocasts whitelisted ops (the MXU ops) to bfloat16 at
dispatch time; bf16 needs no loss scaling (8-bit exponent == fp32 range), so
GradScaler is a working parity shim whose scale path only activates for
float16. Level O2 casts whole models via `amp.decorate`.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler",
           "white_list", "black_list"]

# reference fp16_lists.py:20 white/black lists, pruned to our op names
WHITE_LIST = {"matmul", "mm", "bmm", "linear", "weight_only_linear",
              "conv1d", "conv2d", "conv3d",
              "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
              "einsum", "sdpa", "flash_attention"}
BLACK_LIST = {"exp", "log", "softmax", "log_softmax", "cross_entropy",
              "bce", "bce_with_logits", "mse_loss", "l1_loss", "nll_loss",
              "kl_div", "layer_norm", "batch_norm", "group_norm",
              "instance_norm", "reduce_sum", "reduce_mean", "cumsum",
              "logsumexp", "norm", "softmax_with_cross_entropy"}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = "bfloat16"
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_active():
    return _state.enabled


def maybe_cast_inputs(op_name, raw_args):
    """Called from the dispatch core for each op when AMP is active.

    White ops (MXU) get their fp32 inputs cast down to the autocast dtype;
    black ops (numerically sensitive) get autocast-dtype inputs cast UP to
    fp32, mirroring the reference's two-list rewrite
    (`fp16_utils.py:306 cast_model_to_fp16`)."""
    if not _state.enabled:
        return raw_args
    target = to_jax_dtype(_state.dtype)
    in_white = (op_name in WHITE_LIST or op_name in _state.custom_white) \
        and op_name not in _state.custom_black
    in_black = op_name in BLACK_LIST or op_name in _state.custom_black
    if in_white:
        return [a.astype(target)
                if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                for a in raw_args]
    if in_black:
        return [a.astype(jnp.float32)
                if hasattr(a, "dtype") and a.dtype == target else a
                for a in raw_args]
    return raw_args


# Ops that must COMPUTE in fp32 but, under AMP, should emit the autocast
# dtype so the activation stream between MXU ops stays bf16 end to end
# (halves HBM traffic for the residual stream — the TPU-idiomatic policy;
# the reference keeps these fp32 because fp16 lacks the exponent range,
# which bf16 does not).
STREAM_CAST_OUT = {"layer_norm", "softmax"}


def maybe_wrap_op(op_name, fn):
    """Wrap a black-listed stream op so it emits the autocast dtype.
    Runs inside the op closure, so AD sees the casts (cotangents flow
    through them) and jit fuses them into the op's kernel."""
    if not _state.enabled or op_name not in STREAM_CAST_OUT:
        return fn
    import jax as _jax
    target = to_jax_dtype(_state.dtype)

    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        return _jax.tree_util.tree_map(
            lambda x: x.astype(target)
            if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, out)
    return wrapped


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
            _state.custom_black)
    _state.enabled = enable
    _state.dtype = dtype
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to bf16 (optimizer keeps fp32 master weights —
    our Adam-family moments are always fp32, and the update math upcasts)."""
    if level == "O2" and models is not None:
        single = not isinstance(models, (list, tuple))
        ms = [models] if single else list(models)
        for m in ms:
            m.to(dtype=dtype)
        models = ms[0] if single else ms
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """reference `amp/grad_scaler.py:20` / `imperative/amp_auto_cast.cc`.
    For bfloat16 (TPU default) scaling is an identity passthrough; for
    float16 the full dynamic-loss-scaling state machine runs."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def _active(self):
        return self._enable and _state.dtype == "float16"

    def scale(self, loss):
        if not self._active():
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._active():
            return
        import jax.numpy as jnp
        inv = 1.0 / self._scale
        found = False
        for p in (optimizer._parameter_list or []):
            if p._grad is not None:
                g = p._grad * inv
                if bool(jnp.any(~jnp.isfinite(g))):
                    found = True
                p._grad = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._active():
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not (self._active() and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def get_scale(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._scale = d.get("scale", self._scale)
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)
