"""Recurrent layers (reference `python/paddle/nn/layer/rnn.py`,
`operators/rnn_op` cudnn path).

TPU-native design: the whole multi-layer (bi)directional recurrence is ONE
op whose body is `lax.scan` — XLA compiles it to a single fused while loop
on device (the reference needs cuDNN descriptors for the same effect).
Gate order follows the reference: LSTM [i, f, g, o]; GRU [r, z, c] with the
candidate using r∘(W_hc·h) (paddle/cuDNN convention).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.tensor import apply_op
from .. import functional as Fn
from .. import initializer as I
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


# ---------------------------------------------------------------------------
# Cells (eager building blocks)
# ---------------------------------------------------------------------------

class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from ...ops.creation import full
        batch = batch_ref.shape[batch_dim_idx]
        return full([batch, self.hidden_size], init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def impl(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = apply_op("simple_rnn_cell", impl,
                     (inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh), {})
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def impl(x, h, c, wi, wh, bi, bh):
            z = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new
        h_new, c_new = apply_op("lstm_cell", impl,
                                (inputs, h, c, self.weight_ih, self.weight_hh,
                                 self.bias_ih, self.bias_hh), {})
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def impl(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h
        h = apply_op("gru_cell", impl,
                     (inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh), {})
        return h, h


# ---------------------------------------------------------------------------
# Generic cell drivers (API parity with paddle.nn.RNN / BiRNN)
# ---------------------------------------------------------------------------

class RNN(Layer):
    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import stack
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = []
        for t in order:
            xt = inputs[:, t] if time_axis == 1 else inputs[t]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=time_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o_fw, s_fw = self.rnn_fw(inputs, s_fw)
        o_bw, s_bw = self.rnn_bw(inputs, s_bw)
        return concat([o_fw, o_bw], axis=-1), (s_fw, s_bw)


# ---------------------------------------------------------------------------
# Fused multi-layer RNNs — one lax.scan per layer/direction
# ---------------------------------------------------------------------------

class _RNNBase(Layer):
    _mode = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        self.num_directions = num_dir
        g = {"LSTM": 4, "GRU": 3}.get(self._mode, 1)
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._param_names = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * num_dir
            for d in range(num_dir):
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                wi = self.create_parameter([g * hidden_size, in_sz],
                                           weight_ih_attr,
                                           default_initializer=u)
                wh = self.create_parameter([g * hidden_size, hidden_size],
                                           weight_hh_attr,
                                           default_initializer=u)
                bi = self.create_parameter([g * hidden_size], bias_ih_attr,
                                           is_bias=True, default_initializer=u)
                bh = self.create_parameter([g * hidden_size], bias_hh_attr,
                                           is_bias=True, default_initializer=u)
                for n, p in ((f"weight_ih{sfx}", wi), (f"weight_hh{sfx}", wh),
                             (f"bias_ih{sfx}", bi), (f"bias_hh{sfx}", bh)):
                    self.add_parameter(n, p)
                    self._param_names.append(n)

    def _cell_step(self):
        mode = self._mode

        def step(x, h, c, wi, wh, bi, bh):
            z = x @ wi.T + bi + h @ wh.T + bh
            if mode == "LSTM":
                i, f, g_, o = jnp.split(z, 4, axis=-1)
                c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g_)
                h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                return h_new, c_new
            if mode == "GRU":
                gi = x @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                zt = jax.nn.sigmoid(iz + hz)
                ct = jnp.tanh(ic + r * hc)
                h_new = (1 - zt) * ct + zt * h
                return h_new, h_new
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
            h_new = act(z)
            return h_new, h_new
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self._mode == "LSTM"
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        step = self._cell_step()
        params = [getattr(self, n) for n in self._param_names]

        def impl(x, *flat):
            widx = 0
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, C]
            B = x.shape[1]
            h_all, c_all = [], []
            layer_in = x
            for layer in range(nl):
                outs_dir = []
                for d in range(nd):
                    wi, wh, bi, bh = flat[widx:widx + 4]
                    widx += 4
                    h0 = jnp.zeros((B, hs), x.dtype)
                    c0 = jnp.zeros((B, hs), x.dtype)
                    seq = layer_in[::-1] if d == 1 else layer_in

                    def scan_fn(carry, xt):
                        h, c = carry
                        h2, c2 = step(xt, h, c, wi, wh, bi, bh)
                        return (h2, c2), h2
                    (hT, cT), ys = jax.lax.scan(scan_fn, (h0, c0), seq)
                    if d == 1:
                        ys = ys[::-1]
                    outs_dir.append(ys)
                    h_all.append(hT)
                    c_all.append(cT)
                layer_in = (jnp.concatenate(outs_dir, axis=-1)
                            if nd == 2 else outs_dir[0])
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(h_all)  # [nl*nd, B, H]
            if is_lstm:
                return out, h_stack, jnp.stack(c_all)
            return out, h_stack

        res = apply_op(self._mode.lower(), impl, (inputs, *params), {})
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    _mode = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self._mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    _mode = "LSTM"


class GRU(_RNNBase):
    _mode = "GRU"
