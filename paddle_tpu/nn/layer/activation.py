"""Activation layers (reference `python/paddle/nn/layer/activation.py`)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "PReLU", "ELU", "SELU", "CELU",
           "GELU", "Silu", "Swish", "Sigmoid", "Hardsigmoid", "Hardswish",
           "Hardtanh", "Hardshrink", "Softshrink", "Tanhshrink", "Softplus",
           "Softsign", "Tanh", "Mish", "Maxout", "Softmax", "LogSoftmax",
           "LogSigmoid", "ThresholdedReLU", "GLU"]


def _simple(name, fn_name, defaults=()):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        self._args = args
        self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return getattr(F, fn_name)(x, *self._args, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
LeakyReLU = _simple("LeakyReLU", "leaky_relu")
ELU = _simple("ELU", "elu")
SELU = _simple("SELU", "selu")
CELU = _simple("CELU", "celu")
GELU = _simple("GELU", "gelu")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Sigmoid = _simple("Sigmoid", "sigmoid")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh")
Hardshrink = _simple("Hardshrink", "hardshrink")
Softshrink = _simple("Softshrink", "softshrink")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Softplus = _simple("Softplus", "softplus")
Softsign = _simple("Softsign", "softsign")
Tanh = _simple("Tanh", "tanh")
Mish = _simple("Mish", "mish")
Maxout = _simple("Maxout", "maxout")
Softmax = _simple("Softmax", "softmax")
LogSoftmax = _simple("LogSoftmax", "log_softmax")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu")
GLU = _simple("GLU", "glu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
