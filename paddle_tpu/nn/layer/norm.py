"""Norm layers (reference `python/paddle/nn/layer/norm.py`). SyncBatchNorm:
on TPU, batch stats are synchronized across data-parallel shards by running
the mean/var reduction under the mesh (psum inside shard_map) — eager
single-process behaves like BatchNorm, matching the reference's
convert_sync_batchnorm contract."""
from __future__ import annotations

from ...framework.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", I.Constant(0.0)([num_features]))
        self.register_buffer("_variance", I.Constant(1.0)([num_features]))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm(num_channels) (reference
    `fluid/dygraph/nn.py:BatchNorm`)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/GSPMD the batch axis reduction IS global
    (XLA inserts the collective), so forward == BatchNorm here; the class
    exists for API parity + convert_sync_batchnorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            out.weight.set_value(layer.weight.numpy())
            out.bias.set_value(layer.bias.numpy())
            out._mean.set_value(layer._mean.numpy())
            out._variance.set_value(layer._variance.numpy())
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           self._normalized_shape, attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter(self._normalized_shape,
                                           attr=bias_attr, is_bias=True))

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           [num_channels], attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_channels], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm (reference `operators/spectral_norm_op`)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp
        from ...framework.tensor import apply_op
        dim, iters, eps = self._dim, self._power_iters, self._eps

        def impl(w, u, v):
            wm = jnp.moveaxis(w, dim, 0)
            mat = wm.reshape(wm.shape[0], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma
        return apply_op("spectral_norm", impl,
                        (weight, self.weight_u, self.weight_v), {})
