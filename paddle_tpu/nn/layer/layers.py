"""nn.Layer base class (reference `python/paddle/fluid/dygraph/layers.py`).

Holds Parameters + buffers + sublayers; supports hooks, state_dict, and —
the TPU-native addition — functional capture (`paddle_tpu.framework
.functional.functionalize`) that turns any Layer into a pure
(params, buffers, inputs) -> (outputs, new_buffers) function for
jit/grad/pjit.
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from ...framework.dtype import to_jax_dtype
from ...framework.param_attr import ParamAttr
from ...framework.tensor import Parameter, Tensor
from .. import initializer as I

__all__ = ["Layer"]


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id[0]
        HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._full_name = name_scope or type(self).__name__.lower()
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()

    # -- construction -------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        value = init(shape, dtype)
        p = Parameter(value, name=attr.name, trainable=attr.trainable,
                      regularizer=attr.regularizer, need_clip=attr.need_clip)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        else:
            self._non_persistable_buffer_names_set.discard(name)
        return tensor

    # -- attribute routing --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
        elif buffers is not None and name in buffers:
            # assignment to a registered buffer updates it (BN running stats)
            if value is not None and not isinstance(value, Tensor):
                value = Tensor(value)
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- traversal ----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True):
        return [p for _, p in
                self.named_parameters(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        layers_set = layers_set if layers_set is not None else set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix,
                                           include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in
                self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn: Callable):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # -- mode / device ------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = to_jax_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(dt)
        if device is not None:
            import jax
            from ...framework.place import device_for, set_device
            from ...framework import place as _p
            saved = _p._state.place
            pl = set_device(device) if isinstance(device, str) else device
            _p._state.place = saved
            dev = device_for(pl)
            for p in self.parameters():
                p._value = jax.device_put(p._value, dev)
            for b in self.buffers():
                b._value = jax.device_put(b._value, dev)
        return self

    def float(self):
        return self.to(dtype="float32")

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   use_hook=True):
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(
                include_sublayers=include_sublayers):
            # skip non-persistable
            short = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                for part in name.split(".")[:-1]:
                    owner = getattr(owner, part)
            if short in owner._non_persistable_buffer_names_set:
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing = []
        for name, t in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            v = state_dict[name]
            arr = np.asarray(v.numpy() if isinstance(v, Tensor) else v)
            if tuple(arr.shape) != tuple(t.shape):
                raise ValueError(
                    f"shape mismatch for {name}: {arr.shape} vs {t.shape}")
            t.set_value(arr)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            extra.append(f"  ({name}): {sub_repr}")
        body = "\n".join(extra)
        head = type(self).__name__
        return f"{head}(\n{body}\n)" if body else f"{head}()"
