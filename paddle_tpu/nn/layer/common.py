"""Common layers (reference `python/paddle/nn/layer/common.py`)."""
from __future__ import annotations

import numpy as np

from ...framework.param_attr import ParamAttr
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
           "Embedding", "Flatten", "Upsample", "UpsamplingBilinear2D",
           "UpsamplingNearest2D", "Pad1D", "Pad2D", "Pad3D", "Bilinear",
           "CosineSimilarity", "PairwiseDistance", "Identity", "PixelShuffle",
           "Unfold"]


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features] (reference
    `nn/layer/common.py:Linear`; kernel = matmul_v2 → one MXU dot)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self._in_features}, out={self._out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    """reference `nn/layer/common.py:Embedding` / lookup_table_v2 op."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (None if padding_idx is None else
                             padding_idx if padding_idx >= 0 else
                             num_embeddings + padding_idx)
        self._sparse = sparse
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if self._padding_idx is not None:
            w = np.asarray(self.weight.numpy())
            w[self._padding_idx] = 0
            self.weight.set_value(w)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx,
                           sparse=self._sparse)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True,
                         data_format=data_format)


class _PadNd(Layer):
    _df = "NCL"

    def __init__(self, padding, mode="constant", value=0.0, data_format=None,
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format or self._df

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Pad1D(_PadNd):
    _df = "NCL"


class Pad2D(_PadNd):
    _df = "NCHW"


class Pad3D(_PadNd):
    _df = "NCDHW"


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)
