"""paddle.nn.utils (reference `python/paddle/nn/utils/`): weight_norm,
spectral_norm, parameters_to_vector/vector_to_parameters."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Parameter, Tensor
from ..layer.layers import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


def weight_norm(layer: Layer, name="weight", dim=0):
    """Reparametrize weight = g * v / ||v|| via a forward-pre-hook
    (reference `nn/utils/weight_norm_hook.py`)."""
    w = getattr(layer, name)
    dim = dim if dim is not None else 0
    g = Parameter(_norm_except(w._value, dim), name=f"{name}_g")
    v = Parameter(w._value, name=f"{name}_v")
    del layer._parameters[name]
    layer.add_parameter(f"{name}_g", g)
    layer.add_parameter(f"{name}_v", v)

    def hook(l, inputs):
        vv = getattr(l, f"{name}_v")
        gg = getattr(l, f"{name}_g")
        w_new = vv * (gg / Tensor(_norm_except(vv._value, dim)))
        object.__setattr__(l, name, w_new)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    layer._weight_norm_name = name
    hook(layer, ())  # materialize once
    return layer


def remove_weight_norm(layer: Layer, name="weight"):
    handle = getattr(layer, "_weight_norm_handle", None)
    if handle is not None:
        handle.remove()
    w = getattr(layer, name)
    val = w._value if isinstance(w, Tensor) else w
    for pn in (f"{name}_g", f"{name}_v"):
        layer._parameters.pop(pn, None)
    layer.add_parameter(name, Parameter(val, name=name))
    return layer


def spectral_norm(layer: Layer, name="weight", n_power_iterations=1,
                  eps=1e-12, dim=0):
    from ..layer.norm import SpectralNorm
    w = getattr(layer, name)
    sn = SpectralNorm(tuple(w.shape), dim=dim,
                      power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(f"{name}_spectral_norm", sn)
    orig = layer._parameters.pop(name)
    layer.add_parameter(f"{name}_orig", orig)

    def hook(l, inputs):
        object.__setattr__(l, name,
                           sn(getattr(l, f"{name}_orig")))
        return None
    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer


def parameters_to_vector(parameters, name=None):
    vals = [p._value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    off = 0
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._value = v[off:off + n].reshape(tuple(p.shape)).astype(
            p._value.dtype)
        off += n
