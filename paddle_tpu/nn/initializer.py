"""Initializers (reference `python/paddle/fluid/initializer.py`).

Each initializer is a callable (shape, dtype) -> jax array, drawing keys
from the framework PRNG scope so `paddle.seed` reproduces params.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import to_jax_dtype
from ..framework.random import get_rng_key

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "calculate_gain"]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 2:
        f = shape[0] if shape else 1
        return f, f
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # paddle fc weights are [in, out]; conv are [out, in, k, k]
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4}
    return gains.get(nonlinearity, 1.0)


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        return self.mean + self.std * jax.random.normal(
            get_rng_key(), tuple(shape), to_jax_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        return self.mean + self.std * jax.random.truncated_normal(
            get_rng_key(), -2.0, 2.0, tuple(shape), to_jax_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        return jax.random.uniform(get_rng_key(), tuple(shape),
                                  to_jax_dtype(dtype), self.low, self.high)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(get_rng_key(), tuple(shape),
                                  to_jax_dtype(dtype), -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(get_rng_key(), tuple(shape),
                                       to_jax_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(get_rng_key(), tuple(shape),
                                  to_jax_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(get_rng_key(), tuple(shape),
                                       to_jax_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        from ..framework.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), to_jax_dtype(dtype))
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign init shape {arr.shape} != {tuple(shape)}"
        return arr
