from . import functional, initializer, quant, utils
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.layers import Layer
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from ..framework.param_attr import ParamAttr  # noqa: F401
from ..framework.tensor import Parameter  # noqa: F401

from .clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                   ClipGradByValue)
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401,E402
