"""Attention functionals.

The reference (~v2.0) has no fused attention op — MultiHeadAttention is
composed in Python (`python/paddle/nn/layer/transformer.py:87`). Here
scaled-dot-product attention is a first-class functional with a Pallas
flash-attention fast path on TPU (paddle_tpu/ops/pallas_ops.py), a
segment-aware splash fast path for PACKED sequences
(paddle_tpu/ops/splash_ops.py, `segment_ids=`), and a pure jnp fallback
that XLA fuses well on any backend.

Dispatch order for a call with `segment_ids`: splash kernel when the
shape gate passes (seq length >= FLAGS_splash_attention_min_seq, aligned,
TPU or interpret mode), else the dense fallback with the SAME
segment-within-causal mask — so packed batches are always correct and
only the FLOPs story changes. Without segment_ids the existing
flash-vs-dense gate is unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.flags import flag
from ...framework.monitor import STAT_ADD
from ...framework.tensor import apply_op

__all__ = ["scaled_dot_product_attention"]


def _sdpa_ref(q, k, v, mask, scale, is_causal, dropout_p=0.0, rng=None,
              seg=None):
    # q,k,v: [B, H, S, D]; seg: (q_seg [B,S], kv_seg [B,K]) packed-batch
    # segment ids — cross-segment pairs are masked like the splash kernel.
    # KEEP the segment semantics IN SYNC with
    # ops/splash_ops.sdpa_segment_reference (the kernel parity oracle):
    # same equality mask, causal AND, fully-masked rows output zero
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    allowed = None
    if is_causal:
        S, K = s.shape[-2], s.shape[-1]
        # bottom-right aligned: query i sits at absolute position K-S+i, so
        # the KV-cache decode shape (S < K) attends to the whole prefix
        qpos = jnp.arange(S)[:, None] + (K - S)
        allowed = (qpos >= jnp.arange(K)[None, :])[None, None]
    if seg is not None:
        q_seg, kv_seg = seg
        same = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
        allowed = same if allowed is None else jnp.logical_and(allowed,
                                                               same)
    if allowed is not None:
        s = jnp.where(allowed, s, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            s = jnp.where(mask, s, -1e30)
        else:
            s = s + mask
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0:
        # dropout on the softmax probabilities (upscale-in-train), matching
        # the Pallas kernel's in-kernel semantics — NOT on the output
        keep = jax.random.bernoulli(rng, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0).astype(p.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    if seg is not None:
        # fully-masked rows emit zeros (splash kernel semantics), not the
        # uniform mix a -1e30 softmax degenerates to
        out = jnp.where(jnp.any(allowed, axis=-1)[..., None], out,
                        jnp.zeros((), out.dtype))
    return out


def _tpu_platform():
    try:
        plats = {d.platform for d in jax.devices()}
    except Exception:
        return False
    return bool({"tpu", "axon"} & plats)


def _norm_segment_ids(segment_ids):
    """segment_ids: [B, S] array/Tensor shared by q and kv, or a
    (q_seg, kv_seg) pair. Returns raw [B, S] arrays."""
    from ...framework.tensor import Tensor
    if isinstance(segment_ids, (tuple, list)):
        qs, ks = segment_ids
    else:
        qs = ks = segment_ids
    unwrap = lambda x: x._value if isinstance(x, Tensor) \
        else jnp.asarray(x)  # noqa: E731
    return unwrap(qs), unwrap(ks)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None,
                                 segment_ids=None):
    """query/key/value: [batch, num_heads, seq, head_dim] (BHSD).

    segment_ids sits AFTER name so the reference-compatible positional
    contract (..., training, name) is preserved for existing callers.

    segment_ids: packed-sequence segment ids — a [batch, seq] int array
    (shared q/kv) or a (q_seg, kv_seg) pair, non-decreasing along each
    row (io.packing layout). Tokens attend only within their own
    segment (AND causally when is_causal). Mutually exclusive with
    attn_mask; routes to the splash kernel where supported, else to the
    dense segment-masked fallback.
    """
    d = query.shape[-1]
    scale = 1.0 / (d ** 0.5)
    eff_dropout = dropout_p if training else 0.0

    if segment_ids is not None:
        if attn_mask is not None:
            raise ValueError(
                "scaled_dot_product_attention: attn_mask and segment_ids "
                "are mutually exclusive — packed padding is expressed as "
                "a trailing pad segment, not a key-padding mask")
        q_seg, kv_seg = _norm_segment_ids(segment_ids)
        use_splash = False
        if flag("FLAGS_use_splash_attention"):
            from ...ops.splash_ops import splash_supported
            if splash_supported(tuple(query.shape), tuple(key.shape),
                                tuple(value.shape), is_causal=is_causal):
                if flag("FLAGS_flash_attention_interpret"):
                    # interpreter mode has no TPU PRNG lowering → no dropout
                    use_splash = eff_dropout == 0.0
                else:
                    use_splash = _tpu_platform()
        if use_splash:
            from ...framework.tensor import Tensor as _T
            qv = query._value if isinstance(query, _T) else query
            if isinstance(qv, jax.core.Tracer):
                # dispatching from inside a jit trace while a
                # multi-device mesh is live: that trace is (or may be)
                # GSPMD-partitioned, and GSPMD cannot partition a
                # pallas_call — the kernel would gather the GLOBAL
                # batch onto every chip, silently negating dp sharding.
                # The dense fallback partitions fine; meshes that want
                # the kernel use parallel.spmd.sharded_splash_attention
                # (shard_map) explicitly. Concrete (eager) inputs are
                # never pjit-partitioned, mesh or no mesh.
                try:
                    from ...parallel.mesh import get_mesh
                    mesh = get_mesh()
                except Exception:
                    mesh = None
                if mesh is not None and mesh.devices.size > 1:
                    use_splash = False
        if use_splash:
            from ...ops.splash_ops import splash_attention
            STAT_ADD("STAT_splash_dispatches")
            return splash_attention(query, key, value, q_seg, kv_seg,
                                    causal=is_causal, scale=scale,
                                    dropout_p=eff_dropout)
        rng = None
        if eff_dropout > 0.0:
            from ...framework.random import get_rng_key
            rng = get_rng_key()

        def seg_impl(q, k, v):
            return _sdpa_ref(q, k, v, None, scale, is_causal, eff_dropout,
                             rng, seg=(q_seg, kv_seg))
        return apply_op("sdpa_segment", seg_impl, (query, key, value), {})

    use_flash = False
    if flag("FLAGS_use_flash_attention"):
        from ...ops.pallas_ops import flash_supported
        if flash_supported(tuple(query.shape), tuple(key.shape),
                           tuple(value.shape), attn_mask,
                           is_causal=is_causal):
            if flag("FLAGS_flash_attention_interpret"):
                # interpreter mode has no TPU PRNG lowering → no dropout
                use_flash = eff_dropout == 0.0
            else:
                use_flash = _tpu_platform()

    if use_flash:
        from ...ops.pallas_ops import flash_attention
        return flash_attention(
            query, key, value, causal=is_causal, scale=scale,
            attn_mask=attn_mask, dropout_p=eff_dropout)

    if eff_dropout > 0.0:
        from ...framework.random import get_rng_key
        rng = get_rng_key()
    else:
        rng = None

    def impl(q, k, v, *m):
        mask = m[0] if m else None
        return _sdpa_ref(q, k, v, mask, scale, is_causal, eff_dropout, rng)
    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return apply_op("sdpa", impl, args, {})
