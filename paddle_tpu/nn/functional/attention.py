"""Attention functionals.

The reference (~v2.0) has no fused attention op — MultiHeadAttention is
composed in Python (`python/paddle/nn/layer/transformer.py:87`). Here
scaled-dot-product attention is a first-class functional with a Pallas
flash-attention fast path on TPU (paddle_tpu/ops/pallas_ops.py) and a pure
jnp fallback that XLA fuses well on any backend.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.flags import flag
from ...framework.tensor import apply_op

__all__ = ["scaled_dot_product_attention"]


def _sdpa_ref(q, k, v, mask, scale, is_causal, dropout_p=0.0, rng=None):
    # q,k,v: [B, H, S, D]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if is_causal:
        S, K = s.shape[-2], s.shape[-1]
        # bottom-right aligned: query i sits at absolute position K-S+i, so
        # the KV-cache decode shape (S < K) attends to the whole prefix
        qpos = jnp.arange(S)[:, None] + (K - S)
        s = jnp.where(qpos >= jnp.arange(K)[None, :], s, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            s = jnp.where(mask, s, -1e30)
        else:
            s = s + mask
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0:
        # dropout on the softmax probabilities (upscale-in-train), matching
        # the Pallas kernel's in-kernel semantics — NOT on the output
        keep = jax.random.bernoulli(rng, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0).astype(p.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """query/key/value: [batch, num_heads, seq, head_dim] (BHSD)."""
    d = query.shape[-1]
    scale = 1.0 / (d ** 0.5)

    eff_dropout = dropout_p if training else 0.0
    use_flash = False
    if flag("FLAGS_use_flash_attention"):
        from ...ops.pallas_ops import flash_supported
        if flash_supported(tuple(query.shape), tuple(key.shape),
                           tuple(value.shape), attn_mask,
                           is_causal=is_causal):
            if flag("FLAGS_flash_attention_interpret"):
                # interpreter mode has no TPU PRNG lowering → no dropout
                use_flash = eff_dropout == 0.0
            else:
                try:
                    import jax as _j
                    plats = {dd.platform for dd in _j.devices()}
                    use_flash = "tpu" in plats or "axon" in plats
                except Exception:
                    use_flash = False

    if use_flash:
        from ...ops.pallas_ops import flash_attention
        return flash_attention(
            query, key, value, causal=is_causal, scale=scale,
            attn_mask=attn_mask, dropout_p=eff_dropout)

    if eff_dropout > 0.0:
        from ...framework.random import get_rng_key
        rng = get_rng_key()
    else:
        rng = None

    def impl(q, k, v, *m):
        mask = m[0] if m else None
        return _sdpa_ref(q, k, v, mask, scale, is_causal, eff_dropout, rng)
    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return apply_op("sdpa", impl, args, {})
