"""Normalization functionals (reference `operators/batch_norm_op.*`,
`layer_norm_op.*`, `group_norm_op.*`, `instance_norm_op.*`)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor, apply_op

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Training mode computes batch stats AND (eagerly) updates the running
    buffers in place — matching the reference kernel's side effect
    (`batch_norm_op.cc` MeanOut/VarianceOut). Under functional capture the
    buffer update is recorded by the capture machinery instead."""
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    use_batch = training and not use_global_stats

    def impl(v, rm, rv, *wb):
        ch_ax = v.ndim - 1 if channel_last else 1
        axes = tuple(i for i in range(v.ndim) if i != ch_ax)
        if use_batch:
            mean = jnp.mean(v, axis=axes)
            var = jnp.var(v, axis=axes)
        else:
            mean, var = rm, rv
        shape = [1] * v.ndim
        shape[ch_ax] = v.shape[ch_ax]
        out = (v - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        idx = 0
        if weight is not None:
            out = out * wb[idx].reshape(shape)
            idx += 1
        if bias is not None:
            out = out + wb[idx].reshape(shape)
        return out

    wb = tuple(t for t in (weight, bias) if t is not None)
    out = apply_op("batch_norm", impl, (x, running_mean, running_var) + wb, {})

    if use_batch and isinstance(running_mean, Tensor):
        # eager side effect on the running stats (no grad flows)
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        ch_ax = v.ndim - 1 if channel_last else 1
        axes = tuple(i for i in range(v.ndim) if i != ch_ax)
        m = jnp.mean(v, axis=axes)
        n = int(np.prod([v.shape[a] for a in axes]))
        var_unbiased = jnp.var(v, axis=axes) * (n / max(n - 1, 1))
        running_mean._value = (momentum * running_mean._value
                               + (1 - momentum) * m)
        running_var._value = (momentum * running_var._value
                              + (1 - momentum) * var_unbiased)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(tuple(normalized_shape))

    def impl(v, *wb):
        axes = tuple(range(v.ndim - nd, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) / jnp.sqrt(var + epsilon)
        idx = 0
        if weight is not None:
            out = out * wb[idx]
            idx += 1
        if bias is not None:
            out = out + wb[idx]
        return out
    wb = tuple(t for t in (weight, bias) if t is not None)
    return apply_op("layer_norm", impl, (x,) + wb, {})


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")

    def impl(v, *wb):
        ch_ax = v.ndim - 1 if channel_last else 1
        axes = tuple(i for i in range(2, v.ndim)) if not channel_last else \
            tuple(i for i in range(1, v.ndim - 1))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) / jnp.sqrt(var + eps)
        shape = [1] * v.ndim
        shape[ch_ax] = v.shape[ch_ax]
        idx = 0
        if weight is not None:
            out = out * wb[idx].reshape(shape)
            idx += 1
        if bias is not None:
            out = out + wb[idx].reshape(shape)
        return out
    wb = tuple(t for t in (weight, bias) if t is not None)
    return apply_op("instance_norm", impl, (x,) + wb, {})


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")

    def impl(v, *wb):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[:2]
        spatial = v.shape[2:]
        g = v.reshape(n, num_groups, c // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(v.shape)
        shape = [1, c] + [1] * len(spatial)
        idx = 0
        if weight is not None:
            out = out * wb[idx].reshape(shape)
            idx += 1
        if bias is not None:
            out = out + wb[idx].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    wb = tuple(t for t in (weight, bias) if t is not None)
    return apply_op("group_norm", impl, (x,) + wb, {})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def impl(v):
        ch_ax = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v)
        half = size // 2
        pads = [(0, 0)] * v.ndim
        pads[ch_ax] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(v)
        for i in range(size):
            sl = [slice(None)] * v.ndim
            sl[ch_ax] = slice(i, i + v.shape[ch_ax])
            acc = acc + padded[tuple(sl)]
        return v / jnp.power(k + alpha * acc, beta)
    return apply_op("local_response_norm", impl, (x,), {})
