"""Common functionals: linear / dropout / embedding / interpolate / pad…
(reference `python/paddle/nn/functional/common.py`, `input.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.random import get_rng_key
from ...framework.tensor import Tensor, apply_op
from ...ops.manipulation import pad as _pad_op

__all__ = ["linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "label_smooth", "pad", "interpolate",
           "upsample", "cosine_similarity", "pixel_shuffle", "unfold",
           "bilinear", "pairwise_distance", "normalize", "sequence_mask"]


def linear(x, weight, bias=None, name=None):
    """x @ W + b with paddle weight layout [in_features, out_features]
    (reference `operators/matmul_v2_op` + elementwise_add fusion; on TPU the
    bias add fuses into the MXU matmul epilogue via XLA)."""
    if bias is None:
        return apply_op("linear", jnp.matmul, (x, weight), {})
    return apply_op("linear", lambda v, w, b: jnp.matmul(v, w) + b,
                    (x, weight, bias), {})


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = get_rng_key()

    def impl(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(v.shape)]
        keep = 1.0 - p
        mask = jax.random.bernoulli(key, keep, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(mask, v / keep, 0.0).astype(v.dtype)
        return jnp.where(mask, v, 0.0).astype(v.dtype)
    return apply_op("dropout", impl, (x,), {})


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = get_rng_key()

    def impl(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = 1.0 - p
        a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        mask = jax.random.bernoulli(key, keep, v.shape)
        return (a * jnp.where(mask, v, alpha_p) + b).astype(v.dtype)
    return apply_op("alpha_dropout", impl, (x,), {})


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """reference `operators/lookup_table_v2_op`. sparse is accepted for API
    parity; on TPU the gather lowers to a dynamic-gather HLO either way."""
    def impl(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op("embedding", lambda i, w: impl(i, w), (x, weight), {})


def one_hot(x, num_classes, name=None):
    return apply_op("one_hot",
                    lambda v: jax.nn.one_hot(v, num_classes, dtype="float32"),
                    (x,), {})


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def impl(v):
        k = v.shape[-1]
        return (1 - epsilon) * v + epsilon / k
    if prior_dist is not None:
        return apply_op("label_smooth",
                        lambda v, p: (1 - epsilon) * v + epsilon * p,
                        (label, prior_dist), {})
    return apply_op("label_smooth", impl, (label,), {})


pad = _pad_op


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """reference `operators/interpolate_v2_op` — jax.image.resize backed."""
    def impl(v):
        chan_last = data_format in ("NHWC", "NWC", "NDHWC")
        spatial_nd = v.ndim - 2
        if chan_last:
            spat = v.shape[1:-1]
        else:
            spat = v.shape[2:]
        if size is not None:
            tgt = [int(s.item() if isinstance(s, Tensor) else s)
                   for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = (scale_factor if isinstance(scale_factor, (list, tuple))
                  else [scale_factor] * spatial_nd)
            tgt = [int(d * f) for d, f in zip(spat, sf)]
        jmode = {"nearest": "nearest", "bilinear": "bilinear",
                 "trilinear": "trilinear", "bicubic": "bicubic",
                 "linear": "linear", "area": "linear"}[mode]
        if chan_last:
            out_shape = (v.shape[0], *tgt, v.shape[-1])
        else:
            out_shape = (v.shape[0], v.shape[1], *tgt)
        return jax.image.resize(v, out_shape, method=jmode)
    return apply_op("interpolate", impl, (x,), {})


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def impl(a, b):
        num = jnp.sum(a * b, axis=axis)
        d1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        d2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.clip(d1 * d2, eps, None)
    return apply_op("cosine_similarity", impl, (x1, x2), {})


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def impl(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1,
                       keepdims=keepdim) ** (1.0 / p)
    return apply_op("pairwise_distance", impl, (x, y), {})


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def impl(v):
        nrm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.clip(nrm, epsilon, None)
    return apply_op("normalize", impl, (x,), {})


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def impl(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply_op("pixel_shuffle", impl, (x,), {})


def _to2(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _pads4(paddings):
    """Normalize unfold/fold paddings to (top, bottom, left, right).
    The reference 4-element order is [top, LEFT, bottom, right]
    (`operators/unfold_op.h` reads h from paddings[0]/[2], w from
    paddings[1]/[3])."""
    if isinstance(paddings, int):
        return (paddings,) * 4
    if len(paddings) == 2:
        return (paddings[0], paddings[0], paddings[1], paddings[1])
    return (paddings[0], paddings[2], paddings[1], paddings[3])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference `operators/unfold_op`)."""
    kh, kw = _to2(kernel_sizes)
    sh, sw = _to2(strides)
    dh, dw = _to2(dilations)
    pads = _pads4(paddings)

    def impl(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pads[0], pads[1]),
                        (pads[2], pads[3])))
        patches = jax.lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), padding="VALID",
            rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        n2, ckk, oh, ow = patches.shape
        return patches.reshape(n2, ckk, oh * ow)
    return apply_op("unfold", impl, (x,), {})


def bilinear(x1, x2, weight, bias=None, name=None):
    def impl(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply_op("bilinear", impl, args, {})


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    from ...framework.dtype import to_jax_dtype
    ml = maxlen

    def impl(l):
        m = ml if ml is not None else int(l.max())
        rng = jnp.arange(m)
        return (rng[None, :] < l[:, None]).astype(to_jax_dtype(dtype))
    if maxlen is None:
        import numpy as np
        l = np.asarray(lengths._value if isinstance(lengths, Tensor) else lengths)
        m = int(l.max())
        return Tensor(jnp.asarray(
            (np.arange(m)[None, :] < l[:, None]).astype("int64")))
    return apply_op("sequence_mask", impl, (lengths,), {})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im, the inverse of unfold (reference `operators/fold_op.cc`).
    x: [N, C*kh*kw, L] → [N, C, H, W]; overlapping positions sum."""
    H, W = _to2(output_sizes)
    kh, kw = _to2(kernel_sizes)
    sh, sw = _to2(strides)
    dh, dw = _to2(dilations)
    pt, pb, pl, pr = _pads4(paddings)
    oh = (H + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W + pl + pr - (dw * (kw - 1) + 1)) // sw + 1

    def impl(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        v6 = v.reshape(n, c, kh, kw, oh, ow)
        Hp, Wp = H + pt + pb, W + pl + pr
        out = jnp.zeros((n, c, Hp, Wp), v.dtype)
        # static kernel loop (kh*kw slices); each is one strided
        # scatter-add XLA turns into a dynamic-update fusion
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh:i * dh + oh * sh:sh,
                             j * dw:j * dw + ow * sw:sw].add(
                    v6[:, :, i, j])
        return out[:, :, pt:pt + H, pl:pl + W]
    return apply_op("fold", impl, (x,), {})


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at normalized grid locations (reference
    `operators/grid_sampler_op.cc`). x: [N, C, H, W], grid: [N, Hg, Wg, 2]
    in [-1, 1] (last dim = (x, y)). Pure gather + lerp — differentiable in
    both x and grid, no kernel needed."""
    def _unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) * 0.5 * (size - 1)
        return ((coord + 1.0) * size - 1.0) * 0.5

    def _reflect(c, lo, hi):
        rng = hi - lo
        c2 = jnp.abs(jnp.mod(c - lo, 2.0 * rng))
        return lo + jnp.where(c2 > rng, 2.0 * rng - c2, c2)

    def impl(v, g):
        N, C, H, W = v.shape
        ix = _unnormalize(g[..., 0], W)
        iy = _unnormalize(g[..., 1], H)
        if padding_mode == "reflection":
            if align_corners:
                ix = _reflect(ix, 0.0, W - 1.0)
                iy = _reflect(iy, 0.0, H - 1.0)
            else:
                ix = _reflect(ix, -0.5, W - 0.5)
                iy = _reflect(iy, -0.5, H - 0.5)
        if padding_mode in ("border", "reflection"):
            ix = jnp.clip(ix, 0.0, W - 1.0)
            iy = jnp.clip(iy, 0.0, H - 1.0)

        def gather(yi, xi):
            yc = jnp.clip(yi, 0, H - 1)
            xc = jnp.clip(xi, 0, W - 1)
            b = jnp.arange(N)[:, None, None]
            got = v[b, :, yc, xc]              # [N, Hg, Wg, C]
            if padding_mode == "zeros":
                ok = ((yi >= 0) & (yi <= H - 1) &
                      (xi >= 0) & (xi <= W - 1))
                got = got * ok[..., None].astype(got.dtype)
            return got

        if mode == "nearest":
            out = gather(jnp.rint(iy).astype(jnp.int32),
                         jnp.rint(ix).astype(jnp.int32))
            return jnp.moveaxis(out, -1, 1)

        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        wx = (ix - x0)[..., None]
        wy = (iy - y0)[..., None]
        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        tl = gather(y0i, x0i)
        tr = gather(y0i, x0i + 1)
        bl = gather(y0i + 1, x0i)
        br = gather(y0i + 1, x0i + 1)
        top = tl * (1 - wx) + tr * wx
        bot = bl * (1 - wx) + br * wx
        return jnp.moveaxis(top * (1 - wy) + bot * wy, -1, 1)
    return apply_op("grid_sample", impl, (x, grid), {})


__all__ += ["fold", "grid_sample"]
