"""Convolutions (reference `python/paddle/nn/functional/conv.py`,
`operators/conv_op.*`, `conv_cudnn_op.cu`). TPU-native: one
lax.conv_general_dilated per op — XLA tiles it onto the MXU; no
cuDNN-algorithm selection machinery needed."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import apply_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _padding(padding, n):
    """paddle padding: int, list[int] (per-dim), list of pairs, or
    'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _dn(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return (("NHWC", "HWIO", "NHWC") if channel_last
                else ("NCHW", "OIHW", "NCHW"))
    return (("NDHWC", "DHWIO", "NDHWC") if channel_last
            else ("NCDHW", "OIDHW", "NCDHW"))


def _conv(nd, x, weight, bias, stride, padding, dilation, groups,
          data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuple(stride, nd)
    dilation = _tuple(dilation, nd)
    pad = _padding(padding, nd)
    dn = _dn(nd, channel_last)

    def impl(v, w, *rest):
        # weight is always paddle layout [out, in/groups, *k]; convert for
        # channel-last specs
        if channel_last:
            perm = tuple(range(2, 2 + nd)) + (1, 0)
            w = jnp.transpose(w, perm)
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=jax.lax.conv_dimension_numbers(
                v.shape, w.shape, dn))
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op(f"conv{nd}d", impl, args, {})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(1, x, weight, bias, stride, padding, dilation, groups, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(2, x, weight, bias, stride, padding, dilation, groups,
                 data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(3, x, weight, bias, stride, padding, dilation, groups,
                 data_format)


def _conv_transpose(nd, x, weight, bias, stride, padding, output_padding,
                    dilation, groups, data_format):
    """Gradient-of-conv formulation (reference conv2d_transpose semantics =
    torch): lhs-dilate by stride, pad by dilation*(k-1)-p, flip kernel."""
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuple(stride, nd)
    dilation = _tuple(dilation, nd)
    opad = _tuple(output_padding, nd)
    if isinstance(padding, str):
        raise ValueError("string padding unsupported for conv_transpose")
    pads = _padding(padding, nd)
    dn = _dn(nd, False)

    def impl(v, w, *rest):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        # weight paddle layout: [in, out/groups, *k]
        kdims = w.shape[2:]
        # flip spatial dims, swap in/out
        wf = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if groups > 1:
            gi = w.shape[0] // groups
            go = w.shape[1]
            wf = wf.reshape(groups, gi, go, *kdims)
            wf = jnp.swapaxes(wf, 1, 2)  # [g, out/g, in/g, *k]
            wf = wf.reshape(groups * go, gi, *kdims)
        else:
            wf = jnp.swapaxes(wf, 0, 1)
        newpads = []
        for i in range(nd):
            lo, hi = pads[i]
            k = (kdims[i] - 1) * dilation[i]
            newpads.append((k - lo, k - hi + opad[i]))
        out = jax.lax.conv_general_dilated(
            v, wf, window_strides=(1,) * nd, padding=newpads,
            lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=jax.lax.conv_dimension_numbers(
                v.shape, wf.shape, dn))
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1] = b.shape[0]
            out = out + b.reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op(f"conv{nd}d_transpose", impl, args, {})


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(1, x, weight, bias, stride, padding,
                           output_padding, dilation, groups, df)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(2, x, weight, bias, stride, padding,
                           output_padding, dilation, groups, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(3, x, weight, bias, stride, padding,
                           output_padding, dilation, groups, data_format)
