from .activation import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from ...ops.extra_ops import (affine_grid, channel_shuffle,  # noqa: F401
                              gather_tree, max_unpool2d, pixel_unshuffle,
                              temporal_shift)
