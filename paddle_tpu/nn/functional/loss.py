"""Loss functionals (reference `python/paddle/nn/functional/loss.py`,
`operators/softmax_with_cross_entropy_op.*`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor, apply_op

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "mse_loss",
           "l1_loss", "nll_loss", "kl_div", "smooth_l1_loss",
           "binary_cross_entropy", "binary_cross_entropy_with_logits",
           "margin_ranking_loss", "hinge_embedding_loss", "cosine_embedding_loss",
           "triplet_margin_loss", "log_loss", "square_error_cost",
           "sigmoid_focal_loss", "dice_loss", "npair_loss", "ctc_loss"]


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    def impl(logits, lab, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-12, None))
        if soft_label:
            loss = -jnp.sum(lab * logp, axis=axis)
        else:
            lab_ = lab
            if lab_.ndim == logp.ndim:
                lab_ = jnp.squeeze(lab_, axis=axis)
            lab_ = lab_.astype("int32")
            valid = lab_ != ignore_index
            safe = jnp.where(valid, lab_, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis)
            loss = -jnp.squeeze(picked, axis=axis)
            if w:
                wt = jnp.take(w[0], safe)
                loss = loss * wt
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                denom = (jnp.sum(w[0][safe] * valid) if w
                         else jnp.sum(valid.astype(loss.dtype)))
                return jnp.sum(loss) / jnp.clip(denom, 1e-12, None)
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op("cross_entropy", impl, args, {})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # reference keeps label dim
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, [axis])
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op("mse_loss",
                    lambda a, b: _reduce((a - b) ** 2, reduction),
                    (input, label), {})


def square_error_cost(input, label):
    return apply_op("square_error_cost", lambda a, b: (a - b) ** 2,
                    (input, label), {})


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op("l1_loss",
                    lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    (input, label), {})


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def impl(logp, lab, *w):
        lab = lab.astype("int32")
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
        loss = -picked
        if w:
            loss = loss * jnp.take(w[0], safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = (jnp.sum(jnp.take(w[0], safe) * valid) if w
                     else jnp.sum(valid.astype(loss.dtype)))
            return jnp.sum(loss) / jnp.clip(denom, 1e-12, None)
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op("nll_loss", impl, args, {})


def kl_div(input, label, reduction="mean", name=None):
    def impl(logp, target):
        loss = target * (jnp.log(jnp.clip(target, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", impl, (input, label), {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def impl(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", impl, (input, label), {})


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def impl(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op("bce", impl, args, {})


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def impl(z, y, *rest):
        logp = jax.nn.log_sigmoid(z)
        lognotp = jax.nn.log_sigmoid(-z)
        i = 0
        pw = None
        if pos_weight is not None:
            pw = rest[i]; i += 1
        loss = -(y * logp * (pw if pw is not None else 1.0)
                 + (1 - y) * lognotp)
        if weight is not None:
            loss = loss * rest[i]
        return _reduce(loss, reduction)
    args = [logit, label]
    if pos_weight is not None:
        args.append(pos_weight)
    if weight is not None:
        args.append(weight)
    return apply_op("bce_with_logits", impl, tuple(args), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def impl(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)
    return apply_op("margin_ranking_loss", impl, (input, other, label), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def impl(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply_op("hinge_embedding_loss", impl, (input, label), {})


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def impl(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.clip(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12,
            None)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", impl, (input1, input2, label), {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def impl(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos + epsilon) ** p, -1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg + epsilon) ** p, -1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg + epsilon) ** p, -1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply_op("triplet_margin_loss", impl,
                    (input, positive, negative), {})


def log_loss(input, label, epsilon=1e-4, name=None):
    def impl(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply_op("log_loss", impl, (input, label), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def impl(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        pt = p * y + (1 - p) * (1 - y)
        at = alpha * y + (1 - alpha) * (1 - y)
        loss = at * (1 - pt) ** gamma * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply_op("sigmoid_focal_loss", impl, args, {})


def dice_loss(input, label, epsilon=1e-5, name=None):
    def impl(p, y):
        y1 = jax.nn.one_hot(jnp.squeeze(y, -1), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", impl, (input, label), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def impl(a, pos, lab):
        sim = a @ pos.T
        lab = lab.reshape(-1)
        tgt = (lab[:, None] == lab[None, :]).astype(sim.dtype)
        tgt = tgt / jnp.sum(tgt, -1, keepdims=True)
        ce = -jnp.sum(tgt * jax.nn.log_softmax(sim, -1), -1)
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1))
                        + jnp.mean(jnp.sum(pos * pos, -1))) * 0.25
        return jnp.mean(ce) + reg
    return apply_op("npair_loss", impl, (anchor, positive, labels), {})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", name=None):
    """CTC via the standard log-alpha recursion under lax.scan
    (reference `operators/warpctc_op` — here a pure-XLA implementation)."""
    def impl(lp, lab, il, ll):
        # lp: [T, B, C] log probs; lab: [B, S]
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        L = 2 * S + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, L), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], lab[:, :1], axis=1)[:, 0])

        same = jnp.concatenate(
            [jnp.ones((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]],
                                 axis=1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]],
                                 axis=1)
            a2 = jnp.where(same, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(alpha, a1), a2)
            new = m + jnp.log(jnp.exp(alpha - m) + jnp.exp(a1 - m)
                              + jnp.exp(a2 - m))
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return new + emit, None

        alpha, _ = jax.lax.scan(step, alpha0, lp[1:])
        idx_last = 2 * ll
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, (idx_last - 1)[:, None],
                                     axis=1)[:, 0]
        m = jnp.maximum(a_last, a_prev)
        ll_total = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
        loss = -ll_total
        if reduction == "mean":
            return jnp.mean(loss / jnp.clip(ll.astype(loss.dtype), 1, None))
        return _reduce(loss, reduction)
    return apply_op("ctc_loss", impl,
                    (log_probs, labels, input_lengths, label_lengths), {})
