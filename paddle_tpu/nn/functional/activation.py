"""Activation functionals (reference `python/paddle/nn/functional/activation.py`,
kernels `paddle/fluid/operators/activation_op.*`). Pure elementwise — XLA
fuses them into surrounding matmuls/convs."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import apply_op

__all__ = ["relu", "relu6", "relu_", "leaky_relu", "prelu", "elu", "selu",
           "celu", "gelu", "silu", "swish", "sigmoid", "hardsigmoid",
           "hardswish", "hardtanh", "hardshrink", "softshrink", "tanhshrink",
           "softplus", "softsign", "tanh", "mish", "maxout", "softmax",
           "log_softmax", "log_sigmoid", "glu", "gumbel_softmax",
           "thresholded_relu"]


def relu(x, name=None):
    return apply_op("relu", jax.nn.relu, (x,), {})


relu_ = relu


def relu6(x, name=None):
    return apply_op("relu6", jax.nn.relu6, (x,), {})


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu",
                    lambda v: jax.nn.leaky_relu(v, negative_slope), (x,), {})


def prelu(x, weight, data_format="NCHW", name=None):
    def impl(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape = [1] * v.ndim
        shape[ch_axis] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)
    return apply_op("prelu", impl, (x, weight), {})


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda v: jax.nn.elu(v, alpha), (x,), {})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu",
                    lambda v: scale * jnp.where(v > 0, v,
                                                alpha * jnp.expm1(v)), (x,), {})


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda v: jax.nn.celu(v, alpha), (x,), {})


def gelu(x, approximate=False, name=None):
    return apply_op("gelu",
                    lambda v: jax.nn.gelu(v, approximate=approximate), (x,), {})


def silu(x, name=None):
    return apply_op("silu", jax.nn.silu, (x,), {})


def swish(x, name=None):
    return silu(x)


def sigmoid(x, name=None):
    return apply_op("sigmoid", jax.nn.sigmoid, (x,), {})


def log_sigmoid(x, name=None):
    return apply_op("log_sigmoid", jax.nn.log_sigmoid, (x,), {})


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op("hardsigmoid",
                    lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), (x,), {})


def hardswish(x, name=None):
    return apply_op("hardswish",
                    lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, (x,), {})


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda v: jnp.clip(v, min, max), (x,), {})


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink",
                    lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0),
                    (x,), {})


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)),
        (x,), {})


def tanhshrink(x, name=None):
    return apply_op("tanhshrink", lambda v: v - jnp.tanh(v), (x,), {})


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        "softplus",
        lambda v: jnp.where(beta * v > threshold, v,
                            jnp.log1p(jnp.exp(beta * v)) / beta), (x,), {})


def softsign(x, name=None):
    return apply_op("softsign", jax.nn.soft_sign, (x,), {})


def tanh(x, name=None):
    return apply_op("tanh", jnp.tanh, (x,), {})


def mish(x, name=None):
    return apply_op("mish",
                    lambda v: v * jnp.tanh(jax.nn.softplus(v)), (x,), {})


def maxout(x, groups, axis=1, name=None):
    def impl(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(shape), axis=ax + 1)
    return apply_op("maxout", impl, (x,), {})


def thresholded_relu(x, threshold=1.0, name=None):
    return apply_op("thresholded_relu",
                    lambda v: jnp.where(v > threshold, v, 0.0), (x,), {})


def softmax(x, axis=-1, dtype=None, name=None):
    def impl(v):
        if dtype is not None:
            from ...framework.dtype import to_jax_dtype
            v = v.astype(to_jax_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)
    return apply_op("softmax", impl, (x,), {})


def log_softmax(x, axis=-1, dtype=None, name=None):
    def impl(v):
        if dtype is not None:
            from ...framework.dtype import to_jax_dtype
            v = v.astype(to_jax_dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)
    return apply_op("log_softmax", impl, (x,), {})


def glu(x, axis=-1, name=None):
    def impl(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return apply_op("glu", impl, (x,), {})


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import get_rng_key
    key = get_rng_key()

    def impl(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            # straight-through estimator
            y = y_hard + (y - jax.lax.stop_gradient(y))
        return y
    return apply_op("gumbel_softmax", impl, (x,), {})
