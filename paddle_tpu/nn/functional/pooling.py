"""Pooling (reference `python/paddle/nn/functional/pooling.py`,
`operators/pool_op.*`) — lax.reduce_window based."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.tensor import apply_op

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d"]


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _pool(nd, x, kernel, stride, padding, kind, ceil_mode, exclusive,
          channel_last):
    kernel = _tuple(kernel, nd)
    stride = _tuple(stride if stride is not None else kernel, nd)
    pads = _pads(padding, nd)

    def impl(v):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        if isinstance(pads, str):
            padcfg = pads
        else:
            padcfg = [(0, 0), (0, 0)] + list(pads)
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else \
                jnp.iinfo(v.dtype).min
            out = jax.lax.reduce_window(v, init, jax.lax.max, window, strides,
                                        padcfg)
        else:
            s = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides,
                                      padcfg)
            if exclusive and not isinstance(padcfg, str):
                ones = jnp.ones_like(v)
                cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                            strides, padcfg)
                out = s / cnt
            else:
                out = s / float(np.prod(kernel))
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op(f"{kind}_pool{nd}d", impl, (x,), {})


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(1, x, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format == "NLC")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(2, x, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format == "NHWC")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(3, x, kernel_size, stride, padding, "max", ceil_mode, True,
                 data_format == "NDHWC")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(1, x, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format == "NLC")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(2, x, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format == "NHWC")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(3, x, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format == "NDHWC")


def _adaptive(nd, x, output_size, kind, channel_last):
    out_sz = _tuple(output_size, nd)

    def impl(v):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        spat = v.shape[2:]
        out = v
        # per-axis adaptive pooling: split axis into out_sz windows
        for ax in range(nd):
            dim = spat[ax]
            o = out_sz[ax]
            axis = 2 + ax
            if o is None or o == dim:
                continue
            starts = [int(np.floor(i * dim / o)) for i in range(o)]
            ends = [int(np.ceil((i + 1) * dim / o)) for i in range(o)]
            segs = []
            red = jnp.max if kind == "max" else jnp.mean
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, s, e, axis=axis)
                segs.append(red(seg, axis=axis, keepdims=True))
            out = jnp.concatenate(segs, axis=axis)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op(f"adaptive_{kind}_pool{nd}d", impl, (x,), {})


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(1, x, output_size, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(2, x, output_size, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(3, x, output_size, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(1, x, output_size, "max", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(2, x, output_size, "max", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(3, x, output_size, "max", False)
