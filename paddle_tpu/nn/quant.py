"""Weight-only quantization primitives (reference direction:
`paddle.nn.quant.weight_quantize` / `weight_only_linear` — the v2.0 slim
toolchain stops at fake-quant, later versions grew the weight-only API).

TPU rationale: serving is HBM-capacity/bandwidth bound, not int-math
bound. Weights store as int8 (4x smaller) or packed int4 (8x smaller,
two nibbles per int8 byte) with per-output-channel fp32 scales, stay
integer in HBM, and dequantize inside the jitted matmul —
`dequant(q) @ x` is a convert+mul XLA fuses into the MXU epilogue, so
the fp32 weight exists only as a fused temporary, never as a resident
buffer. All quantization math is symmetric abs-max:

    scale[o] = max(|W[:, o]|) / qmax        (qmax: 127 int8, 7 int4)
    q        = clip(round(W / scale), -qmax, qmax)
    W'       = q * scale

int4 packing is two-nibbles-per-int8 along the OUTPUT axis: output
channels 2j (low nibble) and 2j+1 (high nibble) share a byte; an odd
channel count pads one zero column that unpacking slices back off.
Nibbles are sign-extended on unpack with int8 arithmetic shifts
(`(b << 4) >> 4` / `b >> 4`), which jit cleanly — the packed tensor
rides the compiled program as an int8 argument.
"""
from __future__ import annotations

import numpy as np

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "quant_bits", "pack_int4", "unpack_int4"]

_ALGOS = {"weight_only_int8": 8, "weight_only_int4": 4}


def quant_bits(algo: str) -> int:
    if algo not in _ALGOS:
        raise ValueError(f"unknown weight-quant algo {algo!r}; expected "
                         f"one of {sorted(_ALGOS)}")
    return _ALGOS[algo]


def _as_np(x) -> np.ndarray:
    from ..framework.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._value
    return np.asarray(x)


def pack_int4(q: np.ndarray) -> np.ndarray:
    """Pack int4 values (int8 storage, range [-7, 7]) two per byte along
    the last axis: column 2j -> low nibble, 2j+1 -> high nibble. An odd
    column count gets one zero pad column."""
    q = np.asarray(q)
    if q.shape[-1] % 2:
        pad = [(0, 0)] * (q.ndim - 1) + [(0, 1)]
        q = np.pad(q, pad)
    lo = q[..., 0::2].astype(np.uint8) & 0x0F
    hi = q[..., 1::2].astype(np.uint8) & 0x0F
    return np.ascontiguousarray((hi << 4) | lo).view(np.int8)


def unpack_int4(packed, out_features: int):
    """Sign-extend packed nibbles back to int8 values in [-8, 7] and
    slice off the odd-count pad column. jnp-traceable (the serving
    dequant path runs this inside the compiled program); also accepts
    numpy."""
    import jax.numpy as jnp
    p = jnp.asarray(packed, jnp.int8)
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)   # arithmetic: signed
    hi = jnp.right_shift(p, 4)
    q = jnp.stack([lo, hi], axis=-1).reshape(*p.shape[:-1], -1)
    return q[..., :int(out_features)]


def weight_quantize(w, algo: str = "weight_only_int8"):
    """Symmetric abs-max per-output-channel weight quantization.

    w: [in_features, out_features] (any array-like / Tensor). Returns
    (q, scale) numpy arrays: int8 `q` is [in, out] for int8 or packed
    [in, ceil(out/2)] for int4; `scale` is fp32 [out]."""
    bits = quant_bits(algo)
    w = _as_np(w).astype(np.float32)
    if w.ndim != 2:
        raise ValueError(f"weight_quantize expects a 2-D [in, out] "
                         f"weight, got shape {tuple(w.shape)}")
    qmax = float(2 ** (bits - 1) - 1)
    scale = (np.maximum(np.abs(w).max(axis=0), 1e-8) / qmax).astype(
        np.float32)
    q = np.clip(np.round(w / scale), -qmax, qmax).astype(np.int8)
    if bits == 4:
        q = pack_int4(q)
    return q, scale


def weight_dequantize(q, scale, algo: str = "weight_only_int8",
                      out_dtype="float32"):
    """Inverse of weight_quantize: [in, out] floating weight. jnp-
    traceable — this is the expression the jitted matmuls fuse."""
    import jax.numpy as jnp
    bits = quant_bits(algo)
    scale = jnp.asarray(scale)
    q = jnp.asarray(q)
    if bits == 4:
        q = unpack_int4(q, scale.shape[-1])
    return q.astype(out_dtype) * scale.astype(out_dtype)


def weight_only_linear(x, weight, weight_scale, bias=None,
                       weight_dtype: str = "int8"):
    """y = x @ dequant(weight) (+ bias) with the dequant staying inside
    the traced computation (int8/int4 weight remains the HBM-resident
    form; XLA fuses convert+mul into the matmul). Tensor in, Tensor
    out — the functional core of quantization.WeightOnlyLinear."""
    from ..framework.tensor import apply_op
    algo = {"int8": "weight_only_int8",
            "int4": "weight_only_int4"}.get(weight_dtype)
    if algo is None:
        raise ValueError(f"weight_dtype must be 'int8' or 'int4', got "
                         f"{weight_dtype!r}")

    def impl(v, q, s, *b):
        w = weight_dequantize(q, s, algo, out_dtype=v.dtype)
        out = v @ w
        if b:
            out = out + b[0]
        return out

    args = (x, weight, weight_scale) + \
        ((bias,) if bias is not None else ())
    return apply_op("weight_only_linear", impl, args, {})
