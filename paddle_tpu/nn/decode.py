"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference: `python/paddle/fluid/layers/rnn.py` (`BeamSearchDecoder`,
`dynamic_decode`) over the C++ `beam_search_op`/`gather_tree_op`. The TPU
redesign keeps the same Decoder protocol (initialize/step/finalize) but
runs the loop host-side over jitted steps — decode is a generate-style
driver loop (same stance as GPT.generate), with gather_tree assembling
the final beams.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import numpy as np

from ..framework.tensor import Tensor, apply_op

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Protocol (reference rnn.py Decoder): initialize -> (inputs,
    states, finished); step -> (outputs, states, next_inputs, finished);
    finalize -> (outputs, final_states)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (reference
    `fluid/layers/rnn.py:BeamSearchDecoder`).

    cell: an RNNCell (LSTMCell/GRUCell/SimpleRNNCell) or any callable
    `(inputs, states) -> (out, new_states)`; embedding_fn maps token ids
    to cell inputs; output_fn maps cell outputs to vocab logits.
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers ----------------------------------------------------------
    def _merge(self, t):
        """[batch, beam, ...] -> [batch*beam, ...]"""
        import jax

        def impl(v):
            return v.reshape((-1,) + v.shape[2:])
        return jax.tree_util.tree_map(
            lambda x: apply_op("merge_beam", impl, (x,), {}), t,
            is_leaf=lambda x: isinstance(x, Tensor))

    # -- protocol ---------------------------------------------------------
    def initialize(self, inits):
        """inits: cell states for batch rows -> tiled to beams, with beam
        0 active (score 0) and the rest -inf."""
        import jax
        import jax.numpy as jnp

        leaves = jax.tree_util.tree_leaves(
            inits, is_leaf=lambda x: isinstance(x, Tensor))
        batch = leaves[0].shape[0]
        B, W = batch, self.beam_size

        def tile(x):
            def impl(v):
                return jnp.repeat(v[:, None], W, axis=1).reshape(
                    (B * W,) + v.shape[1:])
            return apply_op("tile_beam", impl, (x,), {})
        states = jax.tree_util.tree_map(
            tile, inits, is_leaf=lambda x: isinstance(x, Tensor))
        ids = Tensor(jnp.full((B, W), self.start_token, jnp.int32))
        scores = Tensor(jnp.where(jnp.arange(W)[None, :] == 0, 0.0,
                                  -1e9) * jnp.ones((B, 1)))
        finished = Tensor(jnp.zeros((B, W), bool))
        return (ids, scores), states, finished

    def step(self, time, inputs, states, **kwargs):
        import jax
        import jax.numpy as jnp

        ids, scores = inputs
        B, W = ids.shape
        flat_ids = self._merge(ids)
        emb = self.embedding_fn(flat_ids) if self.embedding_fn else flat_ids
        cell_out, new_states = self.cell(emb, states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out

        def impl(lg, sc, fin):
            V = lg.shape[-1]
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            logp = logp.reshape(B, W, V)
            # finished beams only extend with end_token at no cost
            end_mask = jnp.where(jnp.arange(V) == self.end_token,
                                 0.0, -1e9)
            logp = jnp.where(fin[:, :, None], end_mask[None, None, :],
                             logp)
            total = sc[:, :, None] + logp                     # [B,W,V]
            flat = total.reshape(B, W * V)
            top_sc, top_ix = jax.lax.top_k(flat, W)           # [B,W]
            parent = (top_ix // V).astype(jnp.int32)
            token = (top_ix % V).astype(jnp.int32)
            new_fin = jnp.take_along_axis(fin, parent, axis=1) | \
                (token == self.end_token)
            return top_sc, token, parent, new_fin

        finished = kwargs["finished"]
        top_sc, token, parent, new_fin = apply_op(
            "beam_search", impl,
            (logits, scores, finished), {})

        # reorder cell states by parent beam
        def reorder(x):
            def impl_r(v, par):
                v = v.reshape((B, W) + v.shape[1:])
                out = jnp.take_along_axis(
                    v, par.reshape((B, W) + (1,) * (v.ndim - 2)), axis=1)
                return out.reshape((B * W,) + v.shape[2:])
            return apply_op("reorder_beam", impl_r, (x, parent), {})
        new_states = jax.tree_util.tree_map(
            reorder, new_states, is_leaf=lambda x: isinstance(x, Tensor))

        outputs = (token, parent, top_sc)
        next_inputs = (token, top_sc)
        return outputs, new_states, next_inputs, new_fin

    def finalize(self, outputs, final_states, sequence_lengths):
        """outputs: list of per-step (token, parent, score) -> gather_tree
        assembled ids [T, B, W] plus final beam scores."""
        from ..ops.extra_ops import gather_tree
        from ..ops.manipulation import stack
        tokens = stack([o[0] for o in outputs], axis=0)   # [T,B,W]
        parents = stack([o[1] for o in outputs], axis=0)
        seqs = gather_tree(tokens, parents)
        return (seqs, outputs[-1][2]), final_states


def dynamic_decode(decoder: Decoder, inits=None, max_step_num: int = 100,
                   **kwargs) -> Tuple[Any, Any]:
    """Run the decoder until every beam finishes or max_step_num
    (reference `fluid/layers/rnn.py:dynamic_decode`)."""
    import numpy as np

    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    for t in range(int(max_step_num)):
        out, states, inputs, finished = decoder.step(
            t, inputs, states, finished=finished, **kwargs)
        outputs.append(out)
        if bool(np.asarray(finished.numpy()).all()):
            break
    return decoder.finalize(outputs, states, None)
