"""Gradient clipping (reference `python/paddle/fluid/clip.py`:
ClipGradByValue/Norm/GlobalNorm). Operates on (param, grad) lists; also
provides pure-pytree versions used by the functional/jitted train paths."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_by_global_norm_pytree"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)

    def _tree_clip(self, grads):
        """Pure function on a pytree of raw arrays (jit path)."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out

    def _tree_clip(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            v = g._value
            n = jnp.sqrt(jnp.sum(v * v))
            scale = jnp.where(n > self.clip_norm, self.clip_norm / n, 1.0)
            out.append((p, Tensor(v * scale)))
        return out

    def _tree_clip(self, grads):
        def one(g):
            n = jnp.sqrt(jnp.sum(g * g))
            return g * jnp.where(n > self.clip_norm, self.clip_norm / n, 1.0)
        return jax.tree_util.tree_map(one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            sq.append(jnp.sum(g._value.astype("float32") ** 2))
        if not sq:
            return params_grads
        gn = jnp.sqrt(jnp.sum(jnp.stack(sq)))
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
            else:
                out.append((p, Tensor(g._value * scale.astype(g._value.dtype))))
        return out

    def _tree_clip(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        gn = jnp.sqrt(sum(jnp.sum(g.astype("float32") ** 2) for g in leaves))
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        return jax.tree_util.tree_map(
            lambda g: g * scale.astype(g.dtype), grads)


def clip_by_global_norm_pytree(grads, clip_norm):
    return ClipGradByGlobalNorm(clip_norm)._tree_clip(grads)
