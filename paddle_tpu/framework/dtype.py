"""Dtype registry.

Mirrors the reference dtype table (`paddle/fluid/framework/data_type.h`,
`framework.proto` VarType.Type) but is natively a mapping onto XLA element
types via numpy/jax dtypes. bfloat16 is first-class (TPU native), float16
kept for API parity.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = [
    "DType", "convert_dtype", "to_jax_dtype", "to_paddle_dtype_name",
    "is_floating_point_dtype", "is_integer_dtype", "default_float_dtype",
]


class DType:
    """A framework dtype: thin named wrapper over a jax/numpy dtype."""

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = jnp.dtype(np_dtype)

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        try:
            return self.np_dtype == jnp.dtype(_canon(other))
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)


def _canon(d):
    if isinstance(d, DType):
        return d.np_dtype
    if isinstance(d, str):
        alias = _STR_ALIASES.get(d)
        if alias is not None:
            return alias
    return d


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", jnp.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)

_ALL = [bool_, uint8, int8, int16, int32, int64, float16, bfloat16,
        float32, float64, complex64, complex128]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool"] = bool_
_STR_ALIASES = {"bool": np.bool_, "bfloat16": jnp.bfloat16}


def convert_dtype(dtype) -> DType:
    """Normalize any dtype spec (str / numpy / jax / DType) to a DType."""
    if dtype is None:
        return default_float_dtype()
    if isinstance(dtype, DType):
        return dtype
    if isinstance(dtype, str) and dtype in _BY_NAME:
        return _BY_NAME[dtype]
    jd = jnp.dtype(_canon(dtype))
    name = jd.name
    if name in _BY_NAME:
        return _BY_NAME[name]
    raise TypeError(f"Unsupported dtype: {dtype!r}")


def to_jax_dtype(dtype):
    return convert_dtype(dtype).np_dtype


def to_paddle_dtype_name(dtype) -> str:
    return convert_dtype(dtype).name


def is_floating_point_dtype(dtype) -> bool:
    return jnp.issubdtype(to_jax_dtype(dtype), jnp.floating)


def is_integer_dtype(dtype) -> bool:
    return jnp.issubdtype(to_jax_dtype(dtype), jnp.integer)


_default_float: DType = None  # set below


def default_float_dtype() -> DType:
    return _default_float if _default_float is not None else float32


def set_default_float_dtype(d) -> None:
    """Backs paddle.set_default_dtype; only float dtypes are legal
    (reference: `python/paddle/framework/framework.py` set_default_dtype)."""
    dt = convert_dtype(d)
    if not is_floating_point_dtype(dt):
        raise TypeError(
            f"set_default_dtype only supports float dtypes, got {dt}")
    global _default_float
    _default_float = dt
