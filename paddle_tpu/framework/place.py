"""Places and device selection.

Reference: `paddle/fluid/platform/place.h` (CPUPlace/CUDAPlace variants) and
`paddle.set_device`. TPU-native redesign: a Place names a jax device; the
default place drives `jax.default_device` so eager ops run where the user
asked without per-op copies.
"""
from __future__ import annotations

import threading

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace",
    "XPUPlace", "NPUPlace", "set_device", "get_device",
    "default_place", "device_for", "is_compiled_with_cuda",
    "is_compiled_with_tpu", "is_compiled_with_xpu", "is_compiled_with_npu",
    "device_count", "get_cudnn_version",
]


class Place:
    """Names a device. `device()` resolves to the live jax.Device."""

    kind = "cpu"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def device(self) -> jax.Device:
        devs = [d for d in jax.devices() if d.platform == self._platform()]
        if not devs:
            # Graceful fallback: asked-for platform absent (e.g. TPUPlace in a
            # CPU test env) → first available device.
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def _platform(self) -> str:
        return self.kind

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    kind = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "CPUPlace"


class TPUPlace(Place):
    kind = "tpu"

    def _platform(self):
        # Under the axon tunnel the platform may report as 'axon'/'tpu'.
        plats = {d.platform for d in jax.devices()}
        for p in ("tpu", "axon"):
            if p in plats:
                return p
        return "cpu"


class CUDAPlace(Place):
    """API-parity alias: maps onto the accelerator place (there is no CUDA
    in this framework; kept so reference code using CUDAPlace keeps working)."""
    kind = "gpu"

    def _platform(self):
        plats = {d.platform for d in jax.devices()}
        for p in ("gpu", "tpu", "axon"):
            if p in plats:
                return p
        return "cpu"


class CUDAPinnedPlace(Place):
    """Pinned host memory (`platform/place.h` CUDAPinnedPlace). On TPU the
    host side is plain CPU memory — jax manages pinned staging internally —
    so this is the CPU place kept for API parity."""
    kind = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "CUDAPinnedPlace"


class XPUPlace(Place):
    """Kunlun XPU place in the reference; maps to the accelerator place."""
    kind = "xpu"

    def _platform(self):
        plats = {d.platform for d in jax.devices()}
        for p in ("tpu", "axon", "gpu"):
            if p in plats:
                return p
        return "cpu"


class NPUPlace(XPUPlace):
    kind = "npu"


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def get_cudnn_version():
    """No cuDNN on TPU; reference returns None when not compiled with CUDA
    (`python/paddle/device.py` get_cudnn_version)."""
    return None


class _State(threading.local):
    def __init__(self):
        self.place: Place | None = None


_state = _State()


def _auto_place() -> Place:
    plats = {d.platform for d in jax.devices()}
    if "tpu" in plats or "axon" in plats:
        return TPUPlace(0)
    if "gpu" in plats:
        return CUDAPlace(0)
    return CPUPlace()


def default_place() -> Place:
    if _state.place is None:
        _state.place = _auto_place()
    return _state.place


def device_for(place: Place | None = None) -> jax.Device:
    return (place or default_place()).device()


def set_device(device: str) -> Place:
    """paddle.set_device('cpu' | 'tpu' | 'tpu:0' | 'gpu:0')."""
    if isinstance(device, Place):
        _state.place = device
        return device
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = name.lower()
    if name == "cpu":
        place: Place = CPUPlace()
    elif name in ("tpu", "xpu", "npu", "axon"):
        place = TPUPlace(idx)
    elif name in ("gpu", "cuda"):
        place = CUDAPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}")
    _state.place = place
    return place


def get_device() -> str:
    p = default_place()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"{p.kind}:{p.device_id}"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def device_count() -> int:
    return len(jax.devices())
