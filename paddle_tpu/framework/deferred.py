"""Deferred device-resident scalars (async-dispatch friendly loss handles).

JAX dispatch is asynchronous: a jitted train step returns immediately with
a device-resident future, and the Python thread only blocks when something
forces the value to the host (`float`, `np.asarray`, ...). The reference
hot loop called `float(loss)` every batch, turning every step into a
device->host round-trip barrier. `DeferredScalar` keeps the handle on
device so the fit loop can run ahead of the accelerator and only pay one
sync per `log_freq` steps (same overlap trick as jax.block_until_ready
placement in Bradbury et al.'s async dispatch model).

Every materialization bumps `STAT_train_host_syncs` so tests and `bench.py`
can assert the sync budget of a training loop.
"""
from __future__ import annotations

import numpy as np

from .monitor import STAT_ADD

__all__ = ["DeferredScalar", "materialize_many"]


def materialize_many(values):
    """Host floats for a mixed sequence of DeferredScalar / array / number
    values using ONE device->host transfer for all lazy entries (stacked on
    device), instead of one round-trip per handle. Counts a single
    STAT_train_host_syncs. Entries that can't coerce to float (strings,
    None, ...) come back as None. Used by Model.evaluate and
    callbacks.VisualDL."""
    values = list(values)
    lazy = [i for i, v in enumerate(values)
            if isinstance(v, DeferredScalar) and v._host is None]
    out = [v._host if isinstance(v, DeferredScalar) else v for v in values]
    if lazy:
        import jax.numpy as jnp
        stacked = np.asarray(jnp.stack(
            [jnp.asarray(values[i]._dev, "float32") for i in lazy]))
        STAT_ADD("STAT_train_host_syncs")
        for i, f in zip(lazy, stacked):
            values[i]._host = out[i] = float(f)
            values[i]._dev = None
    res = []
    for v in out:
        if v is None or isinstance(v, float):
            res.append(v)
        else:
            try:
                res.append(float(v))
            except (TypeError, ValueError):
                res.append(None)
    return res


class DeferredScalar:
    """A lazy scalar: holds the device array until a host value is forced.

    `float()` / `item()` / `numpy()` / `__array__` block and cache the host
    value (counted in STAT_train_host_syncs once per handle); `.value`
    returns the raw device array without syncing so callers can batch many
    handles into a single transfer (e.g. `jnp.stack` in Model.evaluate).
    """

    __slots__ = ("_dev", "_host")

    def __init__(self, value):
        self._dev = value
        self._host = None

    @property
    def value(self):
        """Device array if not yet materialized, else the cached float."""
        return self._dev if self._host is None else self._host

    def _materialize(self) -> float:
        if self._host is None:
            STAT_ADD("STAT_train_host_syncs")
            self._host = float(np.asarray(self._dev))
            self._dev = None  # release the device handle
        return self._host

    # -- host coercions (each forces at most one sync; cached after) --------
    def __float__(self):
        return self._materialize()

    def __int__(self):
        return int(self._materialize())

    def __bool__(self):
        # float contract: a 0.0 loss must stay falsy (sync point)
        return bool(self._materialize())

    def item(self):
        return self._materialize()

    def numpy(self):
        return np.asarray(self._materialize(), dtype="float32")

    def __array__(self, dtype=None, copy=None):
        return np.asarray(self._materialize(), dtype=dtype)

    def __format__(self, spec):
        return format(self._materialize(), spec)

    def __repr__(self):
        if self._host is not None:
            return f"DeferredScalar({self._host!r})"
        return "DeferredScalar(<device>)"

    # arithmetic/comparison degrade to host floats (sync point)
    def __add__(self, other):
        return self._materialize() + other

    def __radd__(self, other):
        return other + self._materialize()

    def __sub__(self, other):
        return self._materialize() - other

    def __rsub__(self, other):
        return other - self._materialize()

    def __mul__(self, other):
        return self._materialize() * other

    def __rmul__(self, other):
        return other * self._materialize()

    def __truediv__(self, other):
        return self._materialize() / other

    def __rtruediv__(self, other):
        return other / self._materialize()

    def __pow__(self, other):
        return self._materialize() ** other

    def __rpow__(self, other):
        return other ** self._materialize()

    def __neg__(self):
        return -self._materialize()

    def __abs__(self):
        return abs(self._materialize())

    @staticmethod
    def _coerce(other):
        """float(other), or None for non-numeric operands so comparisons
        can return NotImplemented (e.g. `loss == None` in a callback must
        be False, not a TypeError)."""
        try:
            return float(other)
        except (TypeError, ValueError):
            return None

    def __eq__(self, other):
        f = self._coerce(other)
        return NotImplemented if f is None else self._materialize() == f

    def __lt__(self, other):
        f = self._coerce(other)
        return NotImplemented if f is None else self._materialize() < f

    def __le__(self, other):
        f = self._coerce(other)
        return NotImplemented if f is None else self._materialize() <= f

    def __gt__(self, other):
        f = self._coerce(other)
        return NotImplemented if f is None else self._materialize() > f

    def __ge__(self, other):
        f = self._coerce(other)
        return NotImplemented if f is None else self._materialize() >= f

    def __hash__(self):
        return hash(self._materialize())
