"""paddle.save / paddle.load (reference `fluid/dygraph/checkpoint.py:56,128`
save_dygraph/load_dygraph; format: pickled dict of numpy arrays →
`.pdparams` / `.pdopt`)."""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from .tensor import Tensor

__all__ = ["save", "load"]


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.numpy())
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        return np.asarray(obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path: str, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)
