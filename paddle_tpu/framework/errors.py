"""Typed framework errors (reference `paddle/fluid/platform/enforce.h:410`
+ `platform/errors.h`: error codes LEGACY/INVALID_ARGUMENT/NOT_FOUND/
OUT_OF_RANGE/ALREADY_EXISTS/RESOURCE_EXHAUSTED/PRECONDITION_NOT_MET/
PERMISSION_DENIED/EXECUTION_TIMEOUT/UNIMPLEMENTED/UNAVAILABLE/FATAL/
EXTERNAL, raised via PADDLE_ENFORCE_*).

Each type subclasses the closest Python builtin so existing callers that
catch ValueError/KeyError/etc. keep working, while new code can catch the
typed family (all are EnforceNotMet)."""
from __future__ import annotations

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError",
           "OutOfRangeError", "AlreadyExistsError",
           "ResourceExhaustedError", "PreconditionNotMetError",
           "PermissionDeniedError", "ExecutionTimeoutError",
           "UnimplementedError", "UnavailableError", "FatalError",
           "ExternalError", "enforce"]


class EnforceNotMet(Exception):
    """Base of every typed framework error (reference enforce.h:410
    EnforceNotMet). `code` mirrors platform/error_codes.proto."""
    code = "LEGACY"
    # KeyError.__str__ repr-quotes the message; keep plain text for every
    # typed error regardless of which builtin it mixes in
    __str__ = Exception.__str__


class InvalidArgumentError(EnforceNotMet, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet, KeyError):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceNotMet, RuntimeError):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet, PermissionError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet, RuntimeError):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


class ExternalError(EnforceNotMet, OSError):
    code = "EXTERNAL"


def enforce(condition, message="", error_cls=PreconditionNotMetError):
    """PADDLE_ENFORCE: raise `error_cls(message)` unless condition holds."""
    if not condition:
        raise error_cls(message)
