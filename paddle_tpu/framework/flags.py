"""Global flags registry.

Reference: gflags table in `paddle/fluid/platform/flags.cc` +
`pybind/global_value_getter_setter.cc` (paddle.set_flags/get_flags).
Here flags are a plain validated dict; a few map onto jax.config.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable

__all__ = ["set_flags", "get_flags", "register_flag", "flag"]

_FLAGS: Dict[str, Any] = {}


def register_flag(name: str, default: Any, doc: str = "") -> None:
    env = os.environ.get(name)
    if env is not None:
        if isinstance(default, bool):
            default = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            default = int(env)
        elif isinstance(default, float):
            default = float(env)
        else:
            default = env
    _FLAGS[name] = default


# Subset of the reference's 32 flags that are meaningful on TPU, plus ours.
register_flag("FLAGS_check_nan_inf", False,
              "scan op outputs for nan/inf (reference platform/flags.cc:44)")
register_flag("FLAGS_eager_op_jit", True,
              "compile eager ops through a cached jit rather than op-by-op")
register_flag("FLAGS_allocator_strategy", "xla",
              "kept for parity; XLA owns allocation on TPU")
register_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "parity no-op")
register_flag("FLAGS_cudnn_deterministic", False, "parity: deterministic ops")
register_flag("FLAGS_benchmark", False, "sync after every op for timing")
register_flag("FLAGS_use_flash_attention", True,
              "use the Pallas flash-attention kernel on TPU when applicable")
register_flag("FLAGS_flash_attention_interpret", False,
              "force the Pallas flash kernels in interpreter mode (CPU "
              "test meshes; TPU semantics, interpreter speed)")
register_flag("FLAGS_flash_attention_min_seq", 512,
              "shortest query length dispatched to the Pallas flash kernel; "
              "below this XLA's fused dense attention wins (measured "
              "crossover on v5e; see tools/perf_attr.py)")
register_flag("FLAGS_flash_block_q", 512,
              "preferred q tile for the flash/splash attention kernels "
              "(multiple of 128; the on-chip sweep — "
              "tools/perf_flash_sweep.py / perf_splash_sweep.py, v5e, "
              "S=2048, bf16 — picked 512). Kernels fall back to the "
              "largest of 128/256/512/this that divides the sequence")
register_flag("FLAGS_flash_block_kv", 512,
              "preferred kv tile for the flash/splash attention kernels "
              "(multiple of 128; same sweep as FLAGS_flash_block_q)")
register_flag("FLAGS_use_splash_attention", True,
              "use the Pallas segment-aware splash-attention kernel for "
              "scaled_dot_product_attention calls that carry segment_ids "
              "(sequence packing); off routes packed batches through the "
              "dense segment-masked fallback")
register_flag("FLAGS_splash_attention_min_seq", 512,
              "shortest packed-row length dispatched to the splash kernel; "
              "below this the dense segment-masked attention wins (same "
              "crossover assumption as FLAGS_flash_attention_min_seq until "
              "swept on-chip — tools/perf_splash_sweep.py)")
register_flag("FLAGS_use_paged_attention", True,
              "decode-time cached attention over a paged KV cache: on the "
              "TPU backend dispatch to the Pallas paged_attention kernel "
              "(pages stay in place, sequential reads per page); off — or "
              "any non-TPU backend — gathers the page table into a dense "
              "[B,H,T,D] buffer and runs the same masked attention as "
              "GPTModel.generate's fixed cache (the CPU/interpret parity "
              "reference, ops/paged_ops.py)")
register_flag("FLAGS_kv_cache_dtype", "auto",
              "page dtype of serving.PagedKVCache pools: 'auto' stores "
              "pages in the served model's dtype; 'int8' enables the "
              "quantized page mode — int8 pools + per-(layer,head,page) "
              "fp32 scale pools, quantize-on-append / dequantize-on-"
              "read (ops/paged_ops.py), ~4x pages per HBM byte so the "
              "same pool budget admits ~4x the concurrent sequences "
              "(bench.py --mode quant gates >=1.9x at equal bytes); "
              "'float32'/'bfloat16' force an unquantized page dtype")
register_flag("FLAGS_paged_page_size", 16,
              "tokens per KV-cache page (serving.PagedKVCache); the TPU "
              "paged_attention kernel wants a multiple of 8")
register_flag("FLAGS_paged_num_pages", 512,
              "total pages in the per-layer K/V pools (page 0 is a "
              "reserved scratch page, so usable pages = this - 1); "
              "pool HBM = 2·layers·heads·pages·page_size·head_dim·dtype")
register_flag("FLAGS_paged_pages_per_seq", 0,
              "page-table width (most pages one sequence may hold); 0 "
              "derives ceil(max_position_embeddings / page_size) from "
              "the served model")
register_flag("FLAGS_paged_compute_block_pages", 4,
              "pages_per_compute_block for the TPU paged_attention "
              "kernel (kv tile = this * page_size)")
register_flag("FLAGS_gen_max_slots", 8,
              "serving.GenerationEngine: fixed decode-batch slot count — "
              "the ONE compiled decode-step shape; live sequences join "
              "and leave the running batch without recompiling")
register_flag("FLAGS_gen_prefill_buckets", "16,64,256",
              "serving.GenerationEngine: prompt-length buckets a prompt "
              "is right-padded up to, so XLA compiles exactly one "
              "prefill per bucket (clipped to max_position_embeddings)")
register_flag("FLAGS_gen_max_new_tokens", 64,
              "serving.GenerationEngine: default per-request new-token "
              "budget (admission reserves worst-case pages for it)")
register_flag("FLAGS_gen_max_queue_depth", 256,
              "serving.GenerationEngine: pending-request bound; submits "
              "beyond it fail fast with EngineOverloaded")
register_flag("FLAGS_gen_request_timeout_ms", 30000.0,
              "serving.GenerationEngine: default per-request deadline, "
              "enforced while queued AND before every decode step — an "
              "expired sequence is cancelled mid-decode, its pages freed, "
              "only its own future fails (0 disables)")
register_flag("FLAGS_gen_prefix_cache", False,
              "serving.GenerationEngine: content-hash prefix cache over "
              "the paged KV pools (serving/prefix_cache.py) — a request "
              "whose prompt prefix matches a cached block chain maps "
              "those pages read-only (copy-on-write on the one "
              "divergent write) and prefills only the tail; refcount-0 "
              "chains are LRU-evicted before alloc. Opt-in: off keeps "
              "the PR 8 single-owner page semantics exactly")
register_flag("FLAGS_gen_spec_k", 0,
              "serving.GenerationEngine: speculative-decoding draft "
              "tokens per decode step (serving/spec_decode.py prompt-"
              "lookup proposer + ONE fixed-k jitted verify program "
              "scoring k+1 positions over the paged KV cache per "
              "step; the longest greedily-agreeing draft prefix is "
              "accepted plus the bonus token, so a step delivers 1 to "
              "k+1 tokens — greedy output stays token-identical to "
              "speculation off). 0 disables (the plain one-token "
              "decode program)")
register_flag("FLAGS_gen_spec_ngram", 3,
              "serving.GenerationEngine: longest n-gram the prompt-"
              "lookup draft proposer matches against the sequence's "
              "own token history (tried n..1, rightmost match wins); "
              "only read when FLAGS_gen_spec_k > 0")
register_flag("FLAGS_gen_tp", 1,
              "serving.GenerationEngine: tensor-parallel degree of the "
              "lane's mesh slice (ISSUE 19) — every jitted program in "
              "the pack (prefill/tail/decode/verify/cow/zero/tier) is "
              "built as ONE shard_map program over a 'tp' mesh axis "
              "with attention/MLP projection weights and the paged K/V "
              "pools (+ int8 scale grids) head-sharded via "
              "NamedSharding, page tables/lengths/sampling state "
              "replicated, and the row-parallel partial sums psum-"
              "reduced once per block. num_heads and the MLP hidden "
              "width must divide it; 1 = the single-chip lane "
              "(bit-identical to the pre-mesh engine). An explicit "
              "GenerationEngine(mesh=...) overrides the flag")
register_flag("FLAGS_gen_prefill_chunk", 0,
              "serving.GenerationEngine: split prompts longer than "
              "this into fixed-size prefill chunks driven through the "
              "per-bucket tail-extension programs, ONE chunk per "
              "engine iteration interleaved with decode steps — a "
              "long prompt admitting no longer stalls every live "
              "sequence's TPOT for its whole prefill. 0 disables "
              "(whole-prompt bucketed prefill at admission)")
register_flag("FLAGS_gen_prefix_cache_max_pages", 0,
              "serving.GenerationEngine: byte budget for the prefix "
              "cache as a page-count cap — register() eagerly LRU-"
              "evicts cached chains back to this budget (audit code "
              "EVICT_PREFIX_BUDGET) instead of waiting for an "
              "admission to run short of free pages. 0 = unbounded "
              "(evict-on-demand only, the ISSUE 12 behavior)")
register_flag("FLAGS_kv_tier", False,
              "serving.GenerationEngine: host-RAM demotion tier under "
              "the prefix cache (serving/kv_tier.py) — prefix-cache "
              "eviction demotes a cold chain's pages off-device into a "
              "bounded host store (raw int8 bytes + fp32 scale rows, so "
              "the round-trip is exact) instead of discarding them, and "
              "a later lookup that misses HBM but hits the host tier "
              "re-uploads the pages through a double-buffered "
              "device_put pipeline overlapped with the tail prefill. "
              "Requires FLAGS_gen_prefix_cache; off keeps the PR 12 "
              "two-state (HBM or gone) semantics exactly")
register_flag("FLAGS_kv_tier_host_bytes", 256 << 20,
              "serving/kv_tier.py host-store byte budget: demoted page "
              "entries beyond it are LRU-evicted (demote-of-demoted = "
              "final eviction, audit code KV_TIER_EVICT); an entry "
              "that alone exceeds the budget is refused and the "
              "eviction proceeds plain")
register_flag("FLAGS_kv_tier_chunk_pages", 4,
              "pages per upload chunk of the promotion pipeline "
              "(serving/kv_tier.py): the engine device_put-stages chunk "
              "i+1 while chunk i's jitted scatter is in flight — the "
              "double-buffer depth knob, and the fixed width of the ONE "
              "compiled tier_write program (trace-shaping: part of the "
              "program-store content key)")
register_flag("FLAGS_gen_program_store_dir", "",
              "serving.GenerationEngine: root directory of the on-disk "
              "AOT executable store (serving/program_store.py) — warmup "
              "loads serialized prefill/tail/decode/verify/cow programs "
              "under a content key instead of tracing when the key "
              "matches (miss compiles as today, then writes back), so a "
              "fresh PROCESS warm-starts in seconds. Empty = off. "
              "Refused on the CPU backend (the PR 1 aliasing-drop "
              "corruption class, device.serialization_unsafe_backend) "
              "unless FLAGS_gen_program_store_force")
register_flag("FLAGS_gen_program_store_force", False,
              "serving.GenerationEngine: use the program store even on "
              "a backend where device.serialization_unsafe_backend() "
              "is True (XLA:CPU) — emits the one-time PR 1 corruption-"
              "class warning; every load still runs the donation-"
              "aliasing self-check + numeric smoke probe and falls "
              "back to live compile on any mismatch")
register_flag("FLAGS_gen_step_log", True,
              "serving.GenerationEngine: record one compact scheduler "
              "record per engine iteration into the bounded per-engine "
              "step ring (profiler/step_log.py; /steps, chrome counter "
              "tracks, engine_step_ms/gen_queue_age_ms histograms); off "
              "removes the per-iteration accounting entirely "
              "(bench.py --mode generation A/Bs it, <2% gate)")
register_flag("FLAGS_gen_step_log_size", 4096,
              "per-engine step-ring capacity in records; the oldest "
              "record is overwritten (same bounding discipline as "
              "FLAGS_trace_ring_size)")
register_flag("FLAGS_gen_audit_log", "",
              "optional JSONL sink for the generation scheduler's "
              "decision audit log (profiler/audit.py): every "
              "admit/defer/evict/expire/poison decision appends one "
              "reason-coded line to this path; '' keeps the bounded "
              "in-memory ring only")
register_flag("FLAGS_failpoints", "",
              "deterministic fault-injection spec (serving/failpoints.py): "
              "';'-separated `site@trigger[:arg]` terms where trigger is "
              "`N` (fire on the Nth hit only) or `every:K` (every Kth "
              "hit) and arg is a site-specific number (slow_step_ms "
              "sleep). Sites: decode_step_raise, prefill_raise, "
              "decode_poison_nan, alloc_exhaust, slow_step_ms, "
              "kv_tier.promote_upload, kv_tier.demote_gather. '' "
              "disables injection entirely (the zero-cost no-op path)")
register_flag("FLAGS_gen_retry_limit", 2,
              "serving.EngineSupervisor: per-request replay budget — a "
              "request may survive at most this many engine restarts "
              "before it fails with a typed UnavailableError "
              "(audit code RETRY_EXHAUSTED)")
register_flag("FLAGS_gen_restart_backoff_ms", 100.0,
              "serving.EngineSupervisor base backoff between consecutive "
              "engine deaths (doubles per consecutive death, capped at "
              "32x; also the serving lane-restart base backoff)")
register_flag("FLAGS_gen_breaker_threshold", 5,
              "serving.EngineSupervisor crash-storm circuit breaker: "
              "this many engine deaths inside "
              "FLAGS_gen_breaker_window_s opens the breaker — the "
              "supervisor stays down, /readyz reports 503 with the "
              "breaker reason, and pending work fails typed "
              "(audit code BREAKER_OPEN)")
register_flag("FLAGS_gen_breaker_window_s", 30.0,
              "rolling window the crash-storm breaker counts engine "
              "deaths over (see FLAGS_gen_breaker_threshold)")
register_flag("FLAGS_gen_poison_degrade_k", 0,
              "serving.GenerationEngine degraded mode: this many poison "
              "events (non-finite logits) inside "
              "FLAGS_gen_degraded_window_s flips speculative decoding "
              "OFF for the engine (audit code DEGRADED_SPEC_OFF; the "
              "plain decode program is pre-warmed so the flip mints no "
              "compile). 0 disables the detector; snapshotted at "
              "engine construction")
register_flag("FLAGS_gen_exhaust_clamp_k", 0,
              "serving.GenerationEngine degraded mode: this many "
              "page-blocked admission iterations inside "
              "FLAGS_gen_degraded_window_s clamps admission — new "
              "submits that cannot be covered by the pool RIGHT NOW "
              "fail fast with ResourceExhaustedError instead of "
              "queueing toward a timeout (audit code "
              "DEGRADED_ADMIT_CLAMP; clears on the next successful "
              "admission). 0 disables; snapshotted at construction")
register_flag("FLAGS_gen_degraded_window_s", 60.0,
              "rolling window both degraded-mode detectors "
              "(FLAGS_gen_poison_degrade_k / "
              "FLAGS_gen_exhaust_clamp_k) count events over")
register_flag("FLAGS_slo_ttft_p99_ms", 0.0,
              "SLO objective: generative time-to-first-token p99 target "
              "in ms — at most 1% of requests in a window may exceed it "
              "(profiler/slo.py burn rates, /slo, Prometheus gauges); "
              "0 disables the objective")
register_flag("FLAGS_slo_tpot_p99_ms", 0.0,
              "SLO objective: generative time-per-output-token p99 "
              "target in ms (same 1% budget semantics); 0 disables")
register_flag("FLAGS_slo_error_rate", 0.0,
              "SLO objective: max fraction of requests that may fail "
              "(timeout/poison/engine death) per rolling window; "
              "0 disables")
register_flag("FLAGS_slo_windows_s", "60,300",
              "comma-separated rolling-window lengths (seconds) the SLO "
              "burn rates are evaluated over — shortest window first "
              "(the fast-burn window readiness shedding keys on)")
register_flag("FLAGS_slo_max_burn_rate", 0.0,
              "fold SLO burn into /readyz: an engine reports not-ready "
              "while any objective's fast-window burn rate is >= this "
              "value, so the router sheds load BEFORE the error budget "
              "is gone (0 never sheds; 1.0 = shedding exactly at "
              "budget-burn speed)")
register_flag("FLAGS_router_replicas", 2,
              "default replica count for serving.Router when neither "
              "num_replicas nor prebuilt replicas are passed — each "
              "replica is an EngineSupervisor-wrapped GenerationEngine "
              "(serving/router.py)")
register_flag("FLAGS_router_affinity", True,
              "prefix-affinity placement (serving/router.py): steer a "
              "request to the replica whose sketch holds the longest "
              "blake2b chain over the prompt's leading full pages; "
              "False = pure round-robin over undrained replicas (the "
              "bench.py --mode router A/B arm)")
register_flag("FLAGS_router_sketch_digests", 8192,
              "per-replica LRU sketch capacity, in chain digests, the "
              "router's affinity placement matches against — bounds "
              "router memory at 16 bytes/digest per replica; oldest "
              "digests age out first (serving/router.py)")
register_flag("FLAGS_router_pressure_ttl_ms", 50.0,
              "max age of the router's cached per-replica pressure + "
              "health snapshot before a placement refreshes it — the "
              "poll cadence bound on GenerationEngine.pressure(); 0 "
              "refreshes every placement (serving/router.py)")
register_flag("FLAGS_train_step_donate", True,
              "donate the (params, buffers, opt_state) carry into the jitted "
              "train step so XLA updates parameters in place instead of "
              "allocating a second copy of the model state every step; "
              "disable for A/B numerics checks (hapi/model.py)")
register_flag("FLAGS_train_tail_bucketing", True,
              "Model.fit/evaluate/predict with drop_last=False: pad the "
              "partial tail batch up to the loader's batch size (rows "
              "replicated from the last real sample) with a row mask "
              "folded into the loss mean, so the tail reuses the "
              "full-batch executable instead of compiling one extra XLA "
              "program per tail shape. Requires a row-independent forward "
              "(the serving engine's contract; BatchNorm-style cross-row "
              "stats will see the padded rows) and a loss that is a "
              "mean/sum over rows (hapi/model.py falls back to the "
              "unpadded step otherwise)")
register_flag("FLAGS_xla_compilation_cache", True,
              "persist compiled XLA executables across processes so repeat "
              "runs skip recompiles (device/__init__.py wires this into "
              "jax_compilation_cache_dir at import)")
register_flag("FLAGS_xla_compilation_cache_dir",
              os.path.join("~", ".cache", "paddle_tpu", "xla"),
              "directory backing the persistent XLA compilation cache")
register_flag("FLAGS_serving_max_batch_size", 64,
              "serving.InferenceEngine: most request rows coalesced into "
              "one device batch (also the largest default shape bucket)")
register_flag("FLAGS_serving_max_batch_delay_ms", 2.0,
              "serving.InferenceEngine: how long the micro-batcher holds "
              "the first request of a batch open for co-riders before "
              "dispatching a partial batch")
register_flag("FLAGS_serving_batch_buckets", "1,4,16,64",
              "serving.InferenceEngine: comma-separated batch-size buckets "
              "a device batch is padded up to, so XLA compiles exactly one "
              "executable per bucket instead of one per observed batch size")
register_flag("FLAGS_serving_max_queue_depth", 256,
              "serving.InferenceEngine: pending-request bound; submits "
              "beyond it fail fast with EngineOverloaded (backpressure) "
              "instead of growing an unbounded queue")
register_flag("FLAGS_serving_max_inflight", 2,
              "serving.InferenceEngine: device batches a dispatch lane may "
              "have in flight (dispatched but not yet completed). 2 keeps "
              "the device fed while batch N computes (JAX async dispatch); "
              "1 disables pipelining (dispatch blocks until completion)")
register_flag("FLAGS_serving_devices", "",
              "serving.InferenceEngine default device set: '' = every "
              "local device for artifact-path/Config models (one dispatch "
              "lane + Predictor replica per chip), 'all', or a "
              "comma-separated list of local device INDICES ('0,2'); an "
              "integer lane COUNT is only meaningful as the devices= "
              "argument, not through this string flag")
register_flag("FLAGS_serving_request_timeout_ms", 30000.0,
              "serving.InferenceEngine: default per-request deadline, "
              "enforced while queued AND again at completion — a request "
              "that expired while its batch was on-device fails with "
              "ExecutionTimeoutError, never a late result (0 disables)")
register_flag("FLAGS_serving_lane_restarts", 0,
              "serving.InferenceEngine: how many CONSECUTIVE times a "
              "dead dispatch lane is rebuilt in place (fresh threads, "
              "same replica/device) with exponential backoff "
              "(FLAGS_gen_restart_backoff_ms base) before it stays "
              "permanently out of rotation; deaths separated by more "
              "than FLAGS_gen_breaker_window_s reset the budget and "
              "the backoff. 0 keeps the legacy behavior: lane death "
              "permanently shrinks capacity")
register_flag("FLAGS_trace_ring_size", 16384,
              "profiler.tracer: per-thread trace event ring capacity; the "
              "ring overwrites its oldest events instead of growing, so "
              "trace memory stays bounded under serving soak runs")
register_flag("FLAGS_flight_recorder", True,
              "always-on bounded crash context: RecordEvent scopes keep "
              "recording into the per-thread rings even with the profiler "
              "stopped, and the hardened failure paths (serving lane "
              "death, poisoned-batch retry, poisoned donated carry, "
              "DataLoader worker crash) dump a postmortem JSON artifact "
              "(profiler/flight_recorder.py)")
register_flag("FLAGS_flight_recorder_events", 512,
              "how many trailing trace events a flight-recorder dump "
              "includes (the tail of the merged per-thread rings)")
register_flag("FLAGS_flight_recorder_dir", "",
              "directory for flight-recorder dump files; '' = "
              "<tempdir>/paddle_tpu_flightrec")
register_flag("FLAGS_flight_recorder_interval_s", 2.0,
              "period of the flight recorder's background counter "
              "sampler (the periodic monitor snapshots that give a dump "
              "its recent-counters timeline); 0 disables the sampler")
register_flag("FLAGS_flight_recorder_max_dumps", 16,
              "most dump files kept per process; the oldest is pruned "
              "so a crash-looping failure path cannot fill the disk")
register_flag("FLAGS_serving_spans", True,
              "per-request latency attribution: submit() assigns a span "
              "that stamps every pipeline phase (queued/claimed/padded/"
              "dispatched/device_done/sliced/resolved), feeding the "
              "serving_queue_ms/pad_ms/device_ms/resolve_ms histograms, "
              "chrome-trace flow events linking submit to its lane's "
              "dispatch/complete scopes, and the engine.stats() phase "
              "breakdown; off removes the per-request accounting from "
              "the hot path (profiler/spans.py)")
register_flag("FLAGS_device_telemetry_interval_s", 5.0,
              "period of the lazy device-telemetry sampler "
              "(profiler/device_telemetry.py): per-device live HBM "
              "bytes, cumulative compile-ms ledger, estimated train-step "
              "FLOPs/MFU gauges — started by engines, Model.fit and the "
              "MetricsServer; 0 disables telemetry (the sampler idles "
              "and the per-compile cost-analysis retrace is skipped, so "
              "untelemetered training pays nothing; explicit sample() "
              "calls still refresh memory/compile gauges). Runtime "
              "set_flags toggling works in both directions")
register_flag("FLAGS_device_peak_flops", 0.0,
              "per-device peak FLOP/s used for the MFU gauge; 0 = look "
              "up the device kind in the built-in table (TPU v2-v5p "
              "bf16 peaks) — unknown kinds (CPU test hosts) simply "
              "don't export MFU")
register_flag("FLAGS_metrics_port", 0,
              "profiler.exporter.MetricsServer port: serve /metrics "
              "(Prometheus text), /stats (JSON incl. engine lanes) and "
              "/trace (chrome trace) on 127.0.0.1; 0 = off; engines "
              "also accept InferenceEngine(metrics_port=)")
register_flag("FLAGS_trace_propagation", True,
              "fleet-wide trace-context propagation "
              "(profiler/trace_context.py): the Router (or the engine, "
              "for direct submits) mints one 16-hex trace id per "
              "request; it rides placement audits (trace=), supervisor "
              "delegation and replay, per-incarnation GenSpans "
              "(',tid=' reqspan field) and streams, and is emitted as "
              "cross-process-stable 'fleet_request' chrome flow events "
              "that tools/fleet_trace.py links across N replicas' "
              "/trace exports; off = no ids minted, zero per-request "
              "cost")
register_flag("FLAGS_metrics_history_interval_s", 5.0,
              "period of the lazy time-series sampler "
              "(profiler/timeseries.py): every registered monitor "
              "counter (as a rate/s) and gauge (as a level) plus "
              "per-engine pressure() ticks recorded into bounded "
              "per-name rings, served as /history JSON and chrome 'C' "
              "counter tracks; 0 disables sampling (the thread idles; "
              "runtime set_flags toggling works in both directions)")
register_flag("FLAGS_metrics_history_samples", 512,
              "max samples kept per series by the time-series sampler; "
              "bounds /history memory no matter how long the process "
              "runs (ring semantics: oldest samples drop first)")


def set_flags(flags: Dict[str, Any]) -> None:
    from .errors import NotFoundError
    for k, v in flags.items():
        if k not in _FLAGS:
            raise NotFoundError(f"Unknown flag {k!r}")
        _FLAGS[k] = v


def get_flags(names: Iterable[str] | str) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    return {n: _FLAGS[n] for n in names}


def flag(name: str) -> Any:
    return _FLAGS[name]
