import jax as _jax

# Precision follows dtype (reference semantics: float32 matmul IS float32).
# TPU perf comes from explicit bf16 params/activations (amp.auto_cast), where
# this setting is a no-op — the MXU consumes bf16 natively.
_jax.config.update("jax_default_matmul_precision", "highest")

from . import autograd, dtype, errors, flags, monitor, place, random
from .selected_rows import SelectedRows
from .autograd import (backward, enable_grad, grad, in_trace_mode,
                       is_grad_enabled, no_grad, trace_mode)
from .dtype import (DType, convert_dtype, to_jax_dtype, bool_, uint8, int8,
                    int16, int32, int64, float16, bfloat16, float32, float64,
                    complex64, complex128)
from .place import (CPUPlace, CUDAPlace, Place, TPUPlace, get_device,
                    set_device, default_place, device_for)
from .flags import get_flags, set_flags
from .random import seed, get_rng_key, rng_scope
from .tensor import Parameter, Tensor, apply_op, defop, to_tensor
