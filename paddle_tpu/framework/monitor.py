"""Runtime STAT counters (reference `paddle/fluid/platform/monitor.h:44`
StatRegistry/StatValue + the STAT_ADD/STAT_SUB/STAT_RESET macros in
`monitor.h:131`).

Same contract, Python-native: named monotonic/resettable int counters,
thread-safe, globally registered, dumped as one dict for metrics export.
Hot-path framework code (dataloader batches, flash-kernel dispatches,
executor runs) bumps these; they cost one dict lookup + int add.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

__all__ = ["StatValue", "stat_add", "stat_sub", "stat_reset", "stat_get",
           "stat_set", "stat_gauge_add", "all_stats", "stat_time",
           "STAT_ADD", "STAT_SUB",
           "STAT_RESET", "StatHistogram", "histogram", "all_histograms",
           "registered_histograms", "reset_all_stats", "drain_deltas",
           "merge_deltas", "register_gauge", "gauge_kind", "is_gauge_name"]


# -- gauge-name registry ----------------------------------------------------
#
# The ONE place a stat name's gauge-ness is recorded (ISSUE 11 satellite:
# the exporter's suffix list and the relay's per-instance flag used to
# drift independently). Two kinds:
#
#   "level"  — an absolute level (live HBM bytes, MFU, pages in use):
#              rendered as a Prometheus gauge AND skipped by the
#              cross-process delta relay (summing levels across processes
#              corrupts both sides). `stat_set`/`stat_gauge_add` mark
#              their name "level" automatically.
#   "updown" — a counter that legitimately moves both ways (queue
#              depths): rendered as a Prometheus gauge but RELAYED —
#              stat_add/stat_sub deltas sum correctly across processes.
#              Registered explicitly by the owning module.
#
# The Prometheus exporter classifies via `gauge_kind(name)`; the relay
# skips exactly the "level" kind. A name in neither bucket is a plain
# monotone counter.

_gauge_kinds: Dict[str, str] = {}


def register_gauge(name: str, updown: bool = False) -> None:
    """Declare `name` a gauge for the Prometheus exporter. updown=True
    keeps it in the cross-process relay (bidirectional counter);
    updown=False (a pure level) also excludes it from the relay — though
    level gauges normally self-register through stat_set/gauge_add."""
    _gauge_kinds[name] = "updown" if updown else "level"


def _note_level_gauge(name: str) -> None:
    # stat_set/gauge_add call sites are by definition levels; an updown
    # registration wins (it was an explicit owner decision)
    if _gauge_kinds.get(name) != "updown":
        _gauge_kinds[name] = "level"


def gauge_kind(name: str):
    """"level" / "updown" / None for `name` — the single source of truth
    the exporter and the relay both read."""
    k = _gauge_kinds.get(name)
    if k is not None:
        return k
    s = _registry._stats.get(name)
    if s is not None and s.gauge:
        return "level"
    return None


def is_gauge_name(name: str) -> bool:
    """Should `name` render as a Prometheus gauge?"""
    return gauge_kind(name) is not None


class StatValue:
    """One named counter (reference monitor.h:44)."""

    __slots__ = ("name", "_v", "_lock", "gauge")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()
        self.gauge = False  # set() flips it: a level, not a running total

    def increase(self, n: int = 1) -> int:
        with self._lock:
            self._v += n
            return self._v

    def decrease(self, n: int = 1) -> int:
        return self.increase(-n)

    def reset(self) -> int:
        with self._lock:
            self._v = 0
            return 0

    def set(self, v: int) -> int:
        """Overwrite with an absolute level — gauge semantics (device
        telemetry: live HBM bytes, MFU) as opposed to the counters'
        monotone increase. Marks the stat as a gauge, which excludes it
        from the cross-process delta relay (summing levels across
        processes is meaningless)."""
        with self._lock:
            self._v = int(v)
            self.gauge = True
        _note_level_gauge(self.name)
        return self._v

    def gauge_add(self, n: int) -> int:
        """Atomically move a gauge LEVEL by a delta (resource-residency
        gauges: a predictor replica adds its quantized-weight bytes on
        load and subtracts them on collection). Gauge-marked like set(),
        so the relay never sums it across processes."""
        with self._lock:
            self._v += int(n)
            self.gauge = True
            v = self._v
        _note_level_gauge(self.name)
        return v

    def drain(self) -> int:
        """Atomically read-and-zero (the cross-process delta relay: a
        DataLoader worker ships everything accumulated since its last
        ship, exactly once)."""
        with self._lock:
            v = self._v
            self._v = 0
            return v

    def get(self) -> int:
        return self._v


class StatHistogram:
    """Streaming latency histogram: fixed log-spaced buckets, O(1) observe,
    approximate percentiles (error bounded by the ~7% bucket width).

    The serving engine records per-request latency here (p50/p99 without
    retaining per-request state — the same reason the reference exports
    bucketed latency metrics rather than raw samples)."""

    # 10% geometric spacing from 1us to ~1000s expressed in the caller's
    # unit (buckets are unit-agnostic ratios; callers pick ms or ns)
    _BASE = 1.10
    _MIN = 1e-3
    _NBUCKETS = 240

    __slots__ = ("name", "_counts", "_count", "_sum", "_min", "_max",
                 "_lock")

    def __init__(self, name: str):
        self.name = name
        self._counts = [0] * (self._NBUCKETS + 2)  # +underflow +overflow
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = threading.Lock()

    def _bucket(self, v: float) -> int:
        if v < self._MIN:
            return 0
        import math
        i = int(math.log(v / self._MIN) / math.log(self._BASE)) + 1
        return min(i, self._NBUCKETS + 1)

    def observe(self, value: float) -> None:
        i = self._bucket(value)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += value
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    def _percentile_locked(self, p: float) -> float:
        if self._count == 0:
            return 0.0
        rank = max(1, int(round(p / 100.0 * self._count)))
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                if i == 0:
                    return min(self._MIN, self._max)
                # geometric midpoint of the bucket, clamped to
                # observed extremes so p0/p100 stay honest
                lo = self._MIN * (self._BASE ** (i - 1))
                mid = lo * (self._BASE ** 0.5)
                return max(self._min, min(mid, self._max))
        return self._max

    def percentile(self, p: float) -> float:
        """Approximate p-th percentile (p in [0, 100])."""
        with self._lock:
            return self._percentile_locked(p)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def buckets(self):
        """Cumulative histogram as `[(upper_bound, cumulative_count)]`,
        ending with `(inf, count)` — exactly the shape a Prometheus
        `_bucket{le=...}` series wants (log-spaced bounds map one-to-one
        onto `le` labels; see profiler/exporter.py)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            le = (self._MIN * self._BASE ** i if i <= self._NBUCKETS
                  else float("inf"))
            out.append((le, cum))
        return out

    def drain_raw(self):
        """Atomically snapshot-and-reset the raw state as a compact
        picklable blob `(sparse_counts, count, sum, min, max)` — the
        DataLoader worker side of the cross-process relay. Sparse: most
        of the 242 log buckets are empty for any one shipping window."""
        with self._lock:
            if self._count == 0:
                return None
            blob = ({i: c for i, c in enumerate(self._counts) if c},
                    self._count, self._sum, self._min, self._max)
            self._counts = [0] * (self._NBUCKETS + 2)
            self._count = 0
            self._sum = 0.0
            self._min = float("inf")
            self._max = float("-inf")
            return blob

    def merge_raw(self, sparse_counts, count, total, mn, mx) -> None:
        """Fold another histogram's raw state into this one (the parent
        side of the relay). Buckets are fixed and identical in every
        process, so the merge is exact — not a re-observation through
        snapshots, which would quantize twice."""
        with self._lock:
            for i, c in sparse_counts.items():
                self._counts[int(i)] += int(c)
            self._count += int(count)
            self._sum += float(total)
            self._min = min(self._min, float(mn))
            self._max = max(self._max, float(mx))

    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (self._NBUCKETS + 2)
            self._count = 0
            self._sum = 0.0
            self._min = float("inf")
            self._max = float("-inf")

    def snapshot(self) -> Dict[str, float]:
        with self._lock:  # one lock: count/mean/percentiles stay coherent
            count = self._count
            return {"count": count,
                    "mean": round(self._sum / count, 4) if count else 0.0,
                    "p50": round(self._percentile_locked(50), 4),
                    "p99": round(self._percentile_locked(99), 4),
                    "max": round(self._max, 4) if count else 0.0}


class _Registry:
    def __init__(self):
        self._stats: Dict[str, StatValue] = {}
        self._hists: Dict[str, StatHistogram] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> StatValue:
        s = self._stats.get(name)
        if s is None:
            with self._lock:
                s = self._stats.setdefault(name, StatValue(name))
        return s

    def get_hist(self, name: str) -> StatHistogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, StatHistogram(name))
        return h

    def snapshot(self) -> Dict[str, int]:
        # one consistent pass: the registry lock freezes the NAME SET so
        # a concurrent get-or-create can't resize the dict mid-iteration
        # (values are single atomic int reads and need no per-stat lock)
        with self._lock:
            items = sorted(self._stats.items())
        return {n: s.get() for n, s in items}

    def snapshot_hists(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            items = sorted(self._hists.items())
        return {n: h.snapshot() for n, h in items}

    def histograms(self) -> Dict[str, StatHistogram]:
        with self._lock:
            return dict(self._hists)

    def reset_all(self) -> None:
        with self._lock:
            stats = list(self._stats.values())
            hists = list(self._hists.values())
        for s in stats:
            s.reset()
        for h in hists:
            h.reset()


_registry = _Registry()


def stat_add(name: str, n: int = 1) -> int:
    return _registry.get(name).increase(n)


def stat_sub(name: str, n: int = 1) -> int:
    return _registry.get(name).decrease(n)


def stat_reset(name: str) -> int:
    return _registry.get(name).reset()


def stat_get(name: str) -> int:
    return _registry.get(name).get()


def stat_set(name: str, v: int) -> int:
    """Set an absolute gauge level (device telemetry samplers)."""
    return _registry.get(name).set(v)


def stat_gauge_add(name: str, n: int) -> int:
    """Atomically add a (possibly negative) delta to a gauge level —
    for residency gauges whose owners add on construction and subtract
    on teardown (quantized weights, KV pools)."""
    return _registry.get(name).gauge_add(n)


def drain_deltas():
    """Atomically drain every counter and histogram into one picklable
    delta blob (None when nothing was touched). The multiprocess
    DataLoader worker calls this per shipped batch so ANY stat bumped in
    the worker process — packing counters, user collate_fn counters,
    histograms — reaches the trainer's registry instead of dying with
    the fork's private copy. "level" gauges (anything touched via
    `stat_set`) stay process-local and are neither drained nor merged —
    summing a worker's level into the parent would corrupt both sides.
    The gauge registry is authoritative: a name registered "updown"
    relays as deltas even if some code path also flipped the
    per-instance gauge flag on it."""
    with _registry._lock:
        stats = list(_registry._stats.values())
        hists = list(_registry._hists.items())
    out_s = {}
    for s in stats:
        kind = _gauge_kinds.get(s.name)
        if kind == "level" or (kind is None and s.gauge):
            continue
        v = s.drain()
        if v:
            out_s[s.name] = v
    out_h = {}
    for n, h in hists:
        blob = h.drain_raw()
        if blob is not None:
            out_h[n] = blob
    if not out_s and not out_h:
        return None
    return {"stats": out_s, "hists": out_h}


def merge_deltas(delta) -> None:
    """Fold a `drain_deltas()` blob from another process into this
    registry (additive for counters, exact bucket-merge for
    histograms)."""
    if not delta:
        return
    for n, v in delta.get("stats", {}).items():
        _registry.get(n).increase(v)
    for n, blob in delta.get("hists", {}).items():
        _registry.get_hist(n).merge_raw(*blob)


def all_stats() -> Dict[str, int]:
    """Snapshot of every registered counter (reference
    StatRegistry::publish)."""
    return _registry.snapshot()


def reset_all_stats() -> None:
    """Zero every registered counter AND histogram. STAT counters are
    process-global (the serving-engine docstring's contract), so a bench
    or test that measures deltas from a warm process must reset first or
    it inherits counts from whatever ran before."""
    _registry.reset_all()


def histogram(name: str) -> StatHistogram:
    """Globally registered streaming histogram (get-or-create)."""
    return _registry.get_hist(name)


def all_histograms() -> Dict[str, Dict[str, float]]:
    """Snapshot {name: {count, mean, p50, p99, max}} of every histogram."""
    return _registry.snapshot_hists()


def registered_histograms() -> Dict[str, StatHistogram]:
    """The live histogram objects (the Prometheus exporter renders
    `buckets()`/`sum`/`count` directly rather than via snapshots)."""
    return _registry.histograms()


@contextlib.contextmanager
def stat_time(name: str):
    """Accumulate the wall time (ns) of the enclosed block into `name`.

    Used by the training hot loop (`STAT_train_step_ns`) — note that with
    async dispatch this measures Python dispatch latency, not device
    compute; pair with an explicit sync when device time is wanted.
    """
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        stat_add(name, time.perf_counter_ns() - t0)


# macro-style aliases matching the reference spelling
STAT_ADD = stat_add
STAT_SUB = stat_sub
STAT_RESET = stat_reset
