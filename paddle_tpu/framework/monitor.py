"""Runtime STAT counters (reference `paddle/fluid/platform/monitor.h:44`
StatRegistry/StatValue + the STAT_ADD/STAT_SUB/STAT_RESET macros in
`monitor.h:131`).

Same contract, Python-native: named monotonic/resettable int counters,
thread-safe, globally registered, dumped as one dict for metrics export.
Hot-path framework code (dataloader batches, flash-kernel dispatches,
executor runs) bumps these; they cost one dict lookup + int add.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

__all__ = ["StatValue", "stat_add", "stat_sub", "stat_reset", "stat_get",
           "all_stats", "stat_time", "STAT_ADD", "STAT_SUB", "STAT_RESET"]


class StatValue:
    """One named counter (reference monitor.h:44)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def increase(self, n: int = 1) -> int:
        with self._lock:
            self._v += n
            return self._v

    def decrease(self, n: int = 1) -> int:
        return self.increase(-n)

    def reset(self) -> int:
        with self._lock:
            self._v = 0
            return 0

    def get(self) -> int:
        return self._v


class _Registry:
    def __init__(self):
        self._stats: Dict[str, StatValue] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> StatValue:
        s = self._stats.get(name)
        if s is None:
            with self._lock:
                s = self._stats.setdefault(name, StatValue(name))
        return s

    def snapshot(self) -> Dict[str, int]:
        return {n: s.get() for n, s in sorted(self._stats.items())}


_registry = _Registry()


def stat_add(name: str, n: int = 1) -> int:
    return _registry.get(name).increase(n)


def stat_sub(name: str, n: int = 1) -> int:
    return _registry.get(name).decrease(n)


def stat_reset(name: str) -> int:
    return _registry.get(name).reset()


def stat_get(name: str) -> int:
    return _registry.get(name).get()


def all_stats() -> Dict[str, int]:
    """Snapshot of every registered counter (reference
    StatRegistry::publish)."""
    return _registry.snapshot()


@contextlib.contextmanager
def stat_time(name: str):
    """Accumulate the wall time (ns) of the enclosed block into `name`.

    Used by the training hot loop (`STAT_train_step_ns`) — note that with
    async dispatch this measures Python dispatch latency, not device
    compute; pair with an explicit sync when device time is wanted.
    """
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        stat_add(name, time.perf_counter_ns() - t0)


# macro-style aliases matching the reference spelling
STAT_ADD = stat_add
STAT_SUB = stat_sub
STAT_RESET = stat_reset
