"""Async sharded checkpointing (SURVEY §5 checkpoint/resume: "TPU
equivalent: async sharded checkpoint of replicated/sharded arrays").

Built on orbax (baked into the image): saves/restores the SPMD train state
pytree from `parallel.spmd.make_sharded_train_step` with each array laid
back onto its mesh sharding. Reference counterparts: `fluid/io.py`
save/load + `incubate/checkpoint/auto_checkpoint.py` at single-host scale.
"""
from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["save_sharded", "load_sharded", "AsyncCheckpointer"]


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_sharded(state: Any, path: str, overwrite: bool = True) -> str:
    """Save a (possibly sharded) pytree of jax arrays. Each host writes
    only its addressable shards (orbax OCDBT layout)."""
    path = os.path.abspath(path)
    ckptr = _ckptr()
    if overwrite and os.path.exists(path):
        import shutil
        shutil.rmtree(path, ignore_errors=True)
    ckptr.save(path, state)
    return path


def load_sharded(path: str, target: Optional[Any] = None,
                 shardings: Optional[Any] = None) -> Any:
    """Restore; if `target`/`shardings` given, arrays come back with the
    same NamedShardings (resume onto the same mesh)."""
    import jax
    import orbax.checkpoint as ocp
    ckptr = _ckptr()
    path = os.path.abspath(path)
    if target is None:
        return ckptr.restore(path)
    restore_args = jax.tree_util.tree_map(
        lambda x: ocp.ArrayRestoreArgs(
            sharding=getattr(x, "sharding", None)), target)
    return ckptr.restore(path, restore_args=restore_args)


class AsyncCheckpointer:
    """Background-thread checkpointing so the train loop never blocks on
    IO (reference async PS table save; here: orbax AsyncCheckpointer)."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ck = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path, state):
        import os
        import shutil
        path = os.path.abspath(path)
        if os.path.exists(path):
            shutil.rmtree(path, ignore_errors=True)
        self._ck.save(path, state)

    def wait(self):
        self._ck.wait_until_finished()

    def close(self):
        self.wait()
