"""Tensor: the imperative value type, and the op dispatch core.

Reference: `paddle/fluid/imperative/layer.h:65` (VarBase) +
`pybind/op_function_generator.cc:488` (the generated `core.ops.*` fast path)
+ `framework/tensor.h:89`.

TPU-native redesign: a Tensor wraps a `jax.Array` (device-resident,
XLA-managed memory — no custom allocator needed; reference components #9-10
are subsumed by the XLA runtime). Op dispatch (`defop`) plays the role of
Tracer::TraceOp: unwrap → run the XLA-lowered op eagerly → optionally record
a TapeNode whose pullback is the op's jax.vjp. In trace mode (functional
capture for jit/pjit) the same ops run on jax tracers with the tape off.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .autograd import TapeNode, is_grad_enabled
from .dtype import DType, convert_dtype, to_jax_dtype
from .place import Place, default_place, device_for

__all__ = ["Tensor", "Parameter", "defop", "apply_op", "to_tensor"]

_name_counter = [0]


def _auto_name(prefix="generated_tensor"):
    _name_counter[0] += 1
    return f"{prefix}_{_name_counter[0]}"


class Tensor:
    """Imperative tensor. stop_gradient defaults True (paddle semantics);
    Parameters default False."""

    __slots__ = ("_value", "stop_gradient", "_node", "_grad", "name",
                 "persistable", "__weakref__", "__dict__")

    def __init__(self, value, stop_gradient: bool = True,
                 name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value if isinstance(value, jax.Array) else jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self._node: Optional[TapeNode] = None
        self._grad: Optional[jax.Array] = None
        self.name = name or _auto_name()
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self) -> DType:
        return convert_dtype(self._value.dtype)

    @property
    def place(self) -> str:
        try:
            dev = list(self._value.devices())[0]
            return f"Place({dev.platform}:{dev.id})"
        except Exception:
            return "Place(cpu)"

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad is None:
            return None
        return Tensor(self._grad, stop_gradient=True, name=self.name + "@GRAD")

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad = None
        else:
            self._grad = value._value if isinstance(value, Tensor) else jnp.asarray(value)

    def _accumulate_grad(self, g):
        for hook in getattr(self, "_grad_hooks", ()):
            out = hook(Tensor(g))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else jnp.asarray(out)
        self._grad = g if self._grad is None else self._grad + g

    # -- conversions --------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __float__(self):
        return float(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __bool__(self):
        return bool(np.asarray(self._value))

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_s = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_s},\n       {np.asarray(self._value)!r})")

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self._value, stop_gradient=True, name=self.name)

    def clone(self) -> "Tensor":
        return apply_op("clone", lambda x: x + 0, (self,), {})

    def stop_gradient_(self, flag=True):
        self.stop_gradient = flag
        return self

    # in-place value swap (reference VarBase copy_ / set_value)
    def set_value(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch {v.shape} vs {self._value.shape}")
        self._value = v.astype(self._value.dtype)

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def register_hook(self, hook):
        """Grad hook (reference `imperative/hooks.h`): called with the
        gradient Tensor during backward; a returned Tensor replaces it."""
        if not hasattr(self, "_grad_hooks"):
            self._grad_hooks = []
        self._grad_hooks.append(hook)

        class _Removable:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)
        return _Removable(self._grad_hooks, hook)

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self.to(default_place())

    def cpu(self):
        from .place import CPUPlace
        return self.to(CPUPlace())

    def to(self, place):
        if isinstance(place, str):
            from .place import set_device
            # parse without mutating global default
            from . import place as _p
            saved = _p._state.place
            pl = set_device(place)
            _p._state.place = saved
        else:
            pl = place
        return Tensor(jax.device_put(self._value, device_for(pl)),
                      stop_gradient=self.stop_gradient, name=self.name)

    @property
    def T(self):
        from ..ops import manipulation
        return manipulation.t(self)


class Parameter(Tensor):
    """Trainable tensor (reference `framework.py` Parameter): stop_gradient
    defaults False, persistable True, optional regularizer / need_clip."""

    def __init__(self, value, name=None, trainable=True, regularizer=None,
                 need_clip=True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_distributed = False
        self.optimize_attr = {"learning_rate": 1.0}

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# ---------------------------------------------------------------------------
# op dispatch (the Tracer)
# ---------------------------------------------------------------------------

def _is_inexact(v) -> bool:
    return jnp.issubdtype(jnp.result_type(v), jnp.inexact)


def apply_op(name: str, fn: Callable, args: Sequence[Any], kwargs: dict):
    """Run one op. Mirrors `imperative::Tracer::TraceOp` (tracer.cc:132):
    eager execute + optional grad-node creation."""
    raw_args = []
    diff_pos = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            raw_args.append(a._value)
            if not a.stop_gradient and _is_inexact(a._value):
                diff_pos.append(i)
        else:
            raw_args.append(a)
    raw_kwargs = {k: (v._value if isinstance(v, Tensor) else v)
                  for k, v in kwargs.items()}

    from ..amp import amp_active, maybe_cast_inputs, maybe_wrap_op
    if amp_active():
        raw_args = maybe_cast_inputs(name, raw_args)
        fn = maybe_wrap_op(name, fn)

    # static-graph mode: execute eagerly on placeholder values for
    # shape/dtype propagation AND record the op into the current Program
    # (reference: Python Program building in fluid/framework.py; here the
    # record is replayed through one jax.jit at Executor.run time).
    if not autograd.in_trace_mode():
        from ..static import program as _static
        if _static.in_static_mode():
            def closed_static(*vals):
                full = list(raw_args)
                vi = 0
                for i, a in enumerate(args):
                    if isinstance(a, Tensor):
                        full[i] = vals[vi]
                        vi += 1
                return fn(*full, **raw_kwargs)
            out = fn(*raw_args, **raw_kwargs)
            single = not isinstance(out, (tuple, list))
            flat = [out] if single else list(out)
            outs = [_static.Variable(x) for x in flat]
            tin = [a for a in args if isinstance(a, Tensor)]

            def fn_slots(*vals):
                return closed_static(*vals)
            _static.record_op(name, fn_slots, tin, outs, attrs=raw_kwargs)
            return outs[0] if single else tuple(outs)

    record = bool(diff_pos) and is_grad_enabled()
    if not record:
        out = fn(*raw_args, **raw_kwargs)
        return _wrap_outputs(name, out, None, None)

    def closed(*dvals):
        full = list(raw_args)
        for p, v in zip(diff_pos, dvals):
            full[p] = v
        out = fn(*full, **raw_kwargs)
        # canonicalize sequence outputs (incl. NamedTuples like
        # jnp.linalg's SVDResult) to a plain tuple so the backward walk
        # can feed jax.vjp a matching cotangent pytree
        return tuple(out) if isinstance(out, (tuple, list)) else out

    primals = [raw_args[p] for p in diff_pos]
    out, vjp_fn = jax.vjp(closed, *primals)
    in_tensors = [args[p] for p in diff_pos]
    return _wrap_outputs(name, out, vjp_fn, in_tensors,
                         out_is_seq=isinstance(out, tuple))


def _check_nan_inf(name, out):
    """reference `framework/details/nan_inf_utils_detail.cc` — scan every
    op output when FLAGS_check_nan_inf and abort naming the op."""
    from .flags import flag
    if not flag("FLAGS_check_nan_inf") or autograd.in_trace_mode():
        return
    for x in jax.tree_util.tree_leaves(out):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact):
            if bool(jnp.any(~jnp.isfinite(x))):
                raise FloatingPointError(
                    f"Operator `{name}` output contains NaN/Inf "
                    f"(FLAGS_check_nan_inf is enabled)")


def _wrap_outputs(name, out, vjp_fn, in_tensors, out_is_seq=None):
    _check_nan_inf(name, out)
    single = not isinstance(out, (tuple, list))
    flat = [out] if single else list(out)
    sg = vjp_fn is None
    tensors = [x if isinstance(x, Tensor) else Tensor(x, stop_gradient=sg)
               for x in flat]
    if vjp_fn is not None:
        node = TapeNode(name, vjp_fn, in_tensors, tensors,
                        out_is_seq=out_is_seq)
        for t in tensors:
            t._node = node
            t.stop_gradient = False
    return tensors[0] if single else tuple(tensors)


def defop(name: str = None):
    """Decorator: turn a raw jnp/lax function into a framework op.

    Convention: Tensor-valued arguments are positional; attrs are kwargs
    (mirrors the generated core.ops.* signatures). Output arrays are wrapped
    into Tensors; a TapeNode is recorded when any input requires grad.
    """
    def deco(fn):
        opname = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return apply_op(opname, fn, args, kwargs)

        wrapper.raw = fn
        return wrapper
    return deco


# ---------------------------------------------------------------------------

def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor."""
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(to_jax_dtype(dtype))
        t = Tensor(v, stop_gradient=stop_gradient)
        return t
    if isinstance(data, (list, tuple)) and any(
            isinstance(x, Tensor) for x in jax.tree_util.tree_leaves(data)):
        data = jax.tree_util.tree_map(
            lambda x: x._value if isinstance(x, Tensor) else x, data)
        v = jnp.asarray(data)
    else:
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            arr = arr.astype(np.float32)  # paddle default float32
        v = jnp.asarray(arr)
    if dtype is not None:
        v = v.astype(to_jax_dtype(dtype))
    if place is not None:
        v = jax.device_put(v, device_for(place if isinstance(place, Place)
                                         else None))
    return Tensor(v, stop_gradient=stop_gradient)
