"""Imperative (dygraph) autograd engine.

Reference design: `paddle/fluid/imperative/` — `Tracer::TraceOp` records a
grad node per op (`dygraph_grad_maker.h`) and `BasicEngine::Execute`
(`basic_engine.cc:265`) walks the graph with a GradientAccumulator.

TPU-native redesign: instead of per-op C++ grad kernels, each recorded op
holds the `jax.vjp` pullback of its (already XLA-lowered) forward. Forward
runs eagerly on device; residuals stay on device inside the pullback. The
backward walk is pure Python graph traversal — every numeric step is an XLA
computation. The *fast* path (to_static / Model.fit / fleet) never uses this
engine: it differentiates whole programs with jax.grad, so the per-op tape
only pays off developer ergonomics, exactly like dygraph vs static in the
reference.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import weakref
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "TapeNode", "no_grad", "enable_grad", "is_grad_enabled", "backward",
    "grad", "in_trace_mode", "trace_mode",
]

_node_counter = itertools.count()


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.trace_depth = 0  # >0 ⇒ functional capture; tape disabled


_state = _State()


def is_grad_enabled() -> bool:
    return _state.grad_enabled and _state.trace_depth == 0


@contextlib.contextmanager
def no_grad():
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


def in_trace_mode() -> bool:
    return _state.trace_depth > 0


@contextlib.contextmanager
def trace_mode():
    """Inside: ops run raw (no tape); arrays may be jax tracers."""
    _state.trace_depth += 1
    try:
        yield
    finally:
        _state.trace_depth -= 1


class TapeNode:
    """One recorded op: pullback + graph edges.

    inputs:   Tensors the vjp produces cotangents for (in vjp order).
    out_refs: weakrefs to output Tensors (index-aligned with the flat
              output structure); avals remembered for zero cotangents.
    """

    __slots__ = ("id", "name", "vjp_fn", "inputs", "out_refs", "out_avals",
                 "out_is_seq", "__weakref__")

    def __init__(self, name: str, vjp_fn, inputs: Sequence[Any],
                 out_tensors: Sequence[Any], out_is_seq: bool = None):
        self.id = next(_node_counter)
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)
        self.out_refs = [weakref.ref(t) for t in out_tensors]
        self.out_avals = [(t._value.shape, t._value.dtype)
                          for t in out_tensors]
        # whether vjp_fn expects a tuple cotangent even for ONE output
        # (jax.vjp is strict about the output pytree; a 1-tuple output
        # needs a 1-tuple cotangent)
        self.out_is_seq = (len(out_tensors) > 1 if out_is_seq is None
                           else out_is_seq)

    def __repr__(self):
        return f"TapeNode<{self.name}#{self.id}>"


def _toposort_from(root: TapeNode) -> List[TapeNode]:
    seen = {id(root)}
    stack = [root]
    nodes = [root]
    while stack:
        n = stack.pop()
        for t in n.inputs:
            prev = t._node
            if prev is not None and id(prev) not in seen:
                seen.add(id(prev))
                nodes.append(prev)
                stack.append(prev)
    nodes.sort(key=lambda n: n.id, reverse=True)
    return nodes


def backward(tensor, grad_tensor=None, retain_graph: bool = False) -> None:
    """Tensor.backward(): reference `basic_engine.cc:265` Execute.

    Accumulates `.grad` on every reachable Tensor with stop_gradient=False
    (reference GradientAccumulator semantics: += across backward calls).
    """
    from .tensor import Tensor  # local import, cycle-free at runtime

    if tensor._node is None:
        if not tensor.stop_gradient:
            g = (grad_tensor._value if isinstance(grad_tensor, Tensor)
                 else jnp.ones_like(tensor._value))
            tensor._accumulate_grad(g)
        return

    if grad_tensor is None:
        init = jnp.ones_like(tensor._value)
    else:
        init = (grad_tensor._value if isinstance(grad_tensor, Tensor)
                else jnp.asarray(grad_tensor))

    # cotangent store keyed by Tensor identity
    cots: dict[int, Any] = {id(tensor): init}
    keep_alive: dict[int, Any] = {id(tensor): tensor}

    nodes = _toposort_from(tensor._node)
    for node in nodes:
        if node.vjp_fn is None:
            raise RuntimeError(
                f"backward through {node.name} a second time: the graph was "
                "freed; pass retain_graph=True to the first backward call")
        outs = []
        any_grad = False
        for ref, (shape, dt) in zip(node.out_refs, node.out_avals):
            t = ref()
            g = cots.get(id(t)) if t is not None else None
            if g is None:
                g = jnp.zeros(shape, dt)
            else:
                any_grad = True
            outs.append(g)
        if not any_grad:
            continue
        in_grads = node.vjp_fn(tuple(outs) if node.out_is_seq else outs[0])
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            k = id(t)
            if k in cots:
                cots[k] = cots[k] + g
            else:
                cots[k] = g
                keep_alive[k] = t
        if not retain_graph:
            node.vjp_fn = None

    for k, t in keep_alive.items():
        if not t.stop_gradient:
            t._accumulate_grad(cots[k])


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — reference `imperative/partial_grad_engine.cc`.

    Returns grads of `outputs` w.r.t. `inputs` without touching `.grad`.
    create_graph (double grad) is not supported by the eager tape yet; use
    the functional API (paddle_tpu.incubate.functional) for higher-order.
    """
    from .tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True: use jax-level functional transforms")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    cots: dict[int, Any] = {}
    roots: list[TapeNode] = []
    for o, go in zip(outputs, grad_outputs):
        g = (go._value if isinstance(go, Tensor)
             else jnp.ones_like(o._value) if go is None else jnp.asarray(go))
        cots[id(o)] = cots.get(id(o), 0) + g
        if o._node is not None:
            roots.append(o._node)

    seen, nodes = set(), []
    for r in roots:
        for n in _toposort_from(r):
            if id(n) not in seen:
                seen.add(id(n))
                nodes.append(n)
    nodes.sort(key=lambda n: n.id, reverse=True)

    retain = True if retain_graph is None else retain_graph
    for node in nodes:
        outs = []
        any_grad = False
        for ref, (shape, dt) in zip(node.out_refs, node.out_avals):
            t = ref()
            g = cots.get(id(t)) if t is not None else None
            if g is None:
                g = jnp.zeros(shape, dt)
            else:
                any_grad = True
            outs.append(g)
        if not any_grad or node.vjp_fn is None:
            continue
        in_grads = node.vjp_fn(tuple(outs) if node.out_is_seq else outs[0])
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            k = id(t)
            cots[k] = cots[k] + g if k in cots else g
        if not retain:
            node.vjp_fn = None

    results = []
    for t in inputs:
        g = cots.get(id(t))
        if g is None and not allow_unused:
            raise ValueError("an input Tensor is unused in the graph "
                             "(pass allow_unused=True to get None)")
        results.append(None if g is None else Tensor(g, stop_gradient=True))
    return results
