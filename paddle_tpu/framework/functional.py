"""Functional capture: turn an imperative Layer into a pure function.

This is the TPU-native replacement for the reference's dygraph→static
bridge (`fluid/dygraph/dygraph_to_static/program_translator.py:582`
ConcreteProgram traces the Layer into a ProgramDesc; `partial_program.py`
replays it via the run_program op). Here tracing is jax tracing: run the
Layer's Python forward under `trace_mode` with param/buffer values swapped
for tracers → a jaxpr/HLO. No AST rewriting is needed because data-dependent
Python control flow is disallowed under XLA anyway (use lax.cond/scan —
same constraint the reference's AST transformer enforces by conversion).

functionalize(layer) -> (apply_fn, params, buffers) with
  apply_fn(param_values, buffer_values, rng_key, training, *inputs)
      -> (outputs, new_buffer_values)
pure & jittable; batch-norm style buffer mutation is captured by reading
back the Layer's buffer slots after the traced call.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from .autograd import trace_mode
from .random import rng_scope
from .tensor import Tensor

__all__ = ["functionalize", "tree_unwrap", "tree_wrap", "get_params",
           "get_buffers"]


def tree_unwrap(obj):
    """Tensor→jax.Array on arbitrary nests (None passthrough)."""
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, obj,
        is_leaf=lambda x: isinstance(x, Tensor))


def tree_wrap(obj, stop_gradient=True):
    return jax.tree_util.tree_map(
        lambda x: Tensor(x, stop_gradient=stop_gradient)
        if isinstance(x, (jnp.ndarray, jax.Array)) else x, obj)


def get_params(layer) -> "collections.OrderedDict[str, Tensor]":
    return collections.OrderedDict(
        (n, p) for n, p in layer.named_parameters() if p is not None)


def get_buffers(layer) -> "collections.OrderedDict[str, Tensor]":
    return collections.OrderedDict(
        (n, b) for n, b in layer.named_buffers() if b is not None)


def functionalize(layer, forward: Callable = None):
    """Returns (apply_fn, param_values, buffer_values).

    apply_fn(params: dict, buffers: dict, rng, training: bool, *args,
             **kwargs) -> (out_pytree_of_arrays, new_buffers: dict)
    """
    # every jitted step builder (hapi Model, parallel/spmd|pipeline|
    # localsgd, bench) passes through here right before its first
    # compile — the one choke point to resolve the deferred persistent
    # compile-cache decision (see device.maybe_enable_compilation_cache)
    from ..device import maybe_enable_compilation_cache
    maybe_enable_compilation_cache()
    params = get_params(layer)
    buffers = get_buffers(layer)
    fwd = forward or layer.__call__

    def apply_fn(param_values: Dict[str, Any], buffer_values: Dict[str, Any],
                 rng, training: bool, *args, **kwargs):
        saved_vals = {n: t._value for n, t in params.items()}
        saved_bufs = {n: t._value for n, t in buffers.items()}
        saved_training = [(l, l.training)
                         for l in layer.sublayers(include_self=True)]
        for l, _ in saved_training:
            l.training = training
        for n, t in params.items():
            t._value = param_values[n]
        for n, t in buffers.items():
            t._value = buffer_values[n]
        try:
            with trace_mode(), rng_scope(rng):
                wargs = tree_wrap(args)
                wkwargs = tree_wrap(kwargs)
                out = fwd(*wargs, **wkwargs)
                new_bufs = {n: t._value for n, t in buffers.items()}
                return tree_unwrap(out), new_bufs
        finally:
            for n, t in params.items():
                t._value = saved_vals[n]
            for n, t in buffers.items():
                t._value = saved_bufs[n]
            for l, tr in saved_training:
                l.training = tr

    param_values = {n: t._value for n, t in params.items()}
    buffer_values = {n: t._value for n, t in buffers.items()}
    return apply_fn, param_values, buffer_values
