"""SelectedRows — sparse-row gradients (reference
`paddle/fluid/framework/selected_rows.h`: rows_ + value_ + height_, the
grad type produced by `lookup_table(..., is_sparse=True)` and consumed by
the sparse SGD/Adam kernels and the PS push path).

TPU stance: inside an XLA program a sparse grad is counterproductive —
scatter-add into dense is what the hardware fuses — so SelectedRows lives
at the HOST boundary: embedding-heavy models hand (rows, values) blocks
to the optimizer's sparse path or to the PS/HostEmbedding push without
ever materializing a vocab-sized dense gradient on the host.
"""
from __future__ import annotations

import numpy as np

__all__ = ["SelectedRows", "rows_of_embedding_grad"]


class SelectedRows:
    """rows: int64 [n] ids; value: float [n, ...] rows; height: vocab."""

    def __init__(self, rows, value, height: int):
        self.rows = np.ascontiguousarray(np.asarray(rows, np.int64))
        self.value = np.asarray(value)
        if self.value.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"rows ({self.rows.shape[0]}) and value "
                f"({self.value.shape[0]}) leading dims differ")
        self.height = int(height)

    def merge(self) -> "SelectedRows":
        """Sum duplicate row ids (reference
        `operators/math/selected_rows_functor.cc` MergeAdd)."""
        uniq, inv = np.unique(self.rows, return_inverse=True)
        out = np.zeros((uniq.size,) + self.value.shape[1:],
                       self.value.dtype)
        np.add.at(out, inv, self.value)
        return SelectedRows(uniq, out, self.height)

    def to_dense(self) -> np.ndarray:
        """Materialize the [height, ...] dense tensor (reference
        SelectedRows::Get / GetTensorFromSelectedRows op)."""
        m = self.merge()
        dense = np.zeros((self.height,) + m.value.shape[1:],
                         m.value.dtype)
        dense[m.rows] = m.value
        return dense

    def __repr__(self):
        return (f"SelectedRows(n={self.rows.size}, height={self.height}, "
                f"dim={self.value.shape[1:]})")


def rows_of_embedding_grad(ids, dout, height: int) -> SelectedRows:
    """Build the sparse grad of an embedding lookup: ids [any shape],
    dout [ids.shape + (dim,)] — the per-lookup output cotangent. This is
    what `lookup_table_grad(is_sparse=True)` emits in the reference."""
    ids = np.asarray(ids, np.int64).reshape(-1)
    d = np.asarray(dout)
    return SelectedRows(ids, d.reshape(ids.size, -1), height).merge()
