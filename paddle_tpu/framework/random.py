"""Seed / PRNG management.

The reference uses stateful per-device generators (`paddle.seed`,
`framework/generator.cc`). JAX PRNG is functional; this module bridges the
two: a stateful *scope stack* of PRNG keys. Eager code uses the global
scope (mutating split per draw — same UX as paddle.seed); functionalized
code (jit / to_static / Model.fit) pushes a scope seeded from an explicit
key so random ops stay trace-safe (the number of splits is static per trace).
"""
from __future__ import annotations

import contextlib
import os
import threading

import jax

__all__ = ["seed", "get_rng_key", "rng_scope", "default_seed",
           "get_rng_state", "set_rng_state", "get_cuda_rng_state",
           "set_cuda_rng_state"]

default_seed = 0

# Default to XLA's RBG bit generator: on TPU the threefry2x32 default
# burns VPU cycles per dropout mask (~17% of an ERNIE-base train step),
# while rng-bit-generator is near-free. fold_in/split work identically;
# set PADDLE_TPU_PRNG=threefry2x32 to restore the jax default.
_impl = os.environ.get("PADDLE_TPU_PRNG", "rbg")
if _impl != "threefry2x32":
    jax.config.update("jax_default_prng_impl", _impl)


class _RngScope:
    """Key is materialized lazily: importing the framework must NOT touch
    the XLA backend, or jax.distributed.initialize (multi-host rendezvous
    in distributed/env.py) can no longer run after `import paddle_tpu`."""
    __slots__ = ("key", "_seed")

    def __init__(self, key=None, seed=None):
        self.key = key
        self._seed = seed

    def materialize(self):
        if self.key is None:
            self.key = jax.random.PRNGKey(self._seed)
        return self.key

    def next_key(self):
        self.key, sub = jax.random.split(self.materialize())
        return sub


class _State(threading.local):
    def __init__(self):
        self.stack = [_RngScope(seed=default_seed)]


_state = _State()


def seed(s: int):
    """paddle.seed — reset the global generator (lazily: no backend touch)."""
    _state.stack[0] = _RngScope(seed=int(s))
    return _state.stack[0]


def get_rng_key():
    """Draw a fresh subkey from the innermost scope (stateful split)."""
    return _state.stack[-1].next_key()


def get_rng_state():
    """Snapshot the innermost generator state (reference:
    `paddle.get_cuda_rng_state`, `framework/generator.cc` GetState). The
    state is the raw PRNG key array — one generator per host thread, not
    per device: JAX keys are device-agnostic."""
    return [_state.stack[-1].materialize()]


def set_rng_state(states):
    _state.stack[-1].key = states[0] if isinstance(states, (list, tuple)) \
        else states


# API-parity aliases: there is no CUDA here; the "cuda" generator is the
# accelerator generator, which is the same functional key.
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


@contextlib.contextmanager
def rng_scope(key):
    """Run a block with an explicit PRNG key (used by functional capture)."""
    scope = _RngScope(key)
    _state.stack.append(scope)
    try:
        yield scope
    finally:
        _state.stack.pop()
