"""python -m paddle_tpu.distributed.launch — the reference's
`python -m paddle.distributed.launch` entry (`distributed/launch/main.py`),
same CLI as fleet.launch."""
from .fleet.launch import launch, main  # noqa: F401

if __name__ == "__main__":
    launch()
