"""DataParallel + init_parallel_env surface (reference
`fluid/dygraph/parallel.py:322` DataParallel, `imperative/reducer.cc`
bucketed allreduce).

TPU-native: there is no Reducer. Under SPMD the gradient allreduce is
emitted by XLA from the dp-sharded batch; eager single-process training
needs no comm at all. DataParallel here (a) shards params onto the mesh,
(b) exposes the reference API (scale_loss / apply_collective_grads are
no-ops kept for code compat)."""
from __future__ import annotations

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from .env import ParallelEnv, get_world_size, init_parallel_env

__all__ = ["DataParallel", "ParallelEnv", "init_parallel_env"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        from ..parallel.mesh import get_mesh
        from ..parallel.spmd import shard_params
        if get_mesh() is not None:
            shard_params(layers)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # reference scales by 1/nranks before allreduce; XLA's mean over the
        # dp-sharded batch already accounts for it.
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    load_dict = set_state_dict
    set_dict = set_state_dict
