"""Collective communication API (reference
`python/paddle/distributed/collective.py:101-457` and the 54 NCCL kernels
in `paddle/fluid/operators/collective/` — c_allreduce_*, c_broadcast,
c_allgather, c_reducescatter, send_v2/recv_v2…).

TPU-native: there are no eager comm kernels or comm streams. A collective
is an XLA op over a named mesh axis, legal inside compiled SPMD regions
(shard_map / pjit manual axes). The eager API below therefore has two
modes, mirroring how the reference ops behave at their two call sites:
  * inside an SPMD region (a `shard_ctx` axis is active): lowers to
    lax.psum / all_gather / ppermute / all_to_all on that axis;
  * eager at top level: operates on the sharded global array — for a
    1-process runtime the group is this process's devices and the op is
    computed directly (world_size==1 ⇒ identity), matching reference
    semantics where each rank holds its shard.
Ordering/streams (`c_sync_calc_stream`) are unnecessary: XLA's dataflow
already serializes compute↔comm correctly.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor, apply_op
from .env import get_rank, get_world_size

__all__ = ["ReduceOp", "all_reduce", "all_gather", "broadcast", "reduce",
           "scatter", "barrier", "split", "send", "recv", "alltoall",
           "reduce_scatter", "new_group", "wait", "shard_ctx",
           "current_axis", "get_group"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


class Group:
    def __init__(self, rank, nranks, id=0, axis=None, ranks=None):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.axis = axis  # mesh axis name this group maps onto
        self.ranks = ranks or list(range(nranks))

    @property
    def world_size(self):
        return self.nranks


_groups = {}


def new_group(ranks=None, backend=None, axis=None):
    gid = len(_groups) + 1
    g = Group(get_rank(), len(ranks) if ranks else get_world_size(), gid,
              axis=axis, ranks=ranks)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid)


class _Ctx(threading.local):
    def __init__(self):
        self.axes: List[str] = []


_ctx = _Ctx()


@contextlib.contextmanager
def shard_ctx(*axes: str):
    """Marks an SPMD region (inside shard_map): collective calls bind to
    the innermost axis (or an explicit group's axis)."""
    _ctx.axes.extend(axes)
    try:
        yield
    finally:
        for _ in axes:
            _ctx.axes.pop()


def current_axis(group=None) -> Optional[str]:
    if group is not None and getattr(group, "axis", None):
        return group.axis
    return _ctx.axes[-1] if _ctx.axes else None


def _spmd(x, fn_axis, fallback, group=None):
    axis = current_axis(group)
    if axis is not None:
        return apply_op("collective", lambda v: fn_axis(v, axis), (x,), {})
    return fallback(x)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=True):
    def on_axis(v, axis):
        if op == ReduceOp.SUM:
            return lax.psum(v, axis)
        if op == ReduceOp.MAX:
            return lax.pmax(v, axis)
        if op == ReduceOp.MIN:
            return lax.pmin(v, axis)
        return jnp.exp(lax.psum(jnp.log(v), axis))

    def eager(x):
        # 1-process group: the array already holds every shard this process
        # owns; SUM over group of size world_size==1 is identity.
        return x
    out = _spmd(tensor, on_axis, eager, group)
    if isinstance(tensor, Tensor) and not isinstance(out, Tensor):
        out = Tensor(out)
    tensor._value = out._value if isinstance(out, Tensor) else out
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    def on_axis(v, axis):
        return lax.all_gather(v, axis)

    axis = current_axis(group)
    if axis is not None:
        gathered = apply_op("c_allgather",
                            lambda v: lax.all_gather(v, axis), (tensor,), {})
        if isinstance(tensor_list, list):
            n = gathered.shape[0]
            for i in range(n):
                tensor_list.append(gathered[i])
        return gathered
    tensor_list.append(tensor)
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    axis = current_axis(group)
    if axis is not None:
        def impl(v):
            # select src's value on every member of the axis
            sz = lax.axis_size(axis) if hasattr(lax, "axis_size") else None
            full = lax.all_gather(v, axis)
            return full[src]
        out = apply_op("c_broadcast", impl, (tensor,), {})
        tensor._value = out._value
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axis = current_axis(group)
    if axis is not None and tensor_list:
        from ..ops.manipulation import stack
        stacked = stack(tensor_list, axis=0)

        def impl(v):
            idx = lax.axis_index(axis)
            return lax.dynamic_index_in_dim(v, idx, 0, keepdims=False)
        out = apply_op("c_scatter", impl, (stacked,), {})
        tensor._value = out._value
        return tensor
    if tensor_list:
        tensor._value = tensor_list[src]._value
    return tensor


def reduce_scatter(tensor, input_list_or_tensor, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    axis = current_axis(group)
    src = input_list_or_tensor
    if isinstance(src, (list, tuple)):
        from ..ops.manipulation import concat
        src = concat(list(src), axis=0)
    if axis is not None:
        def impl(v):
            return lax.psum_scatter(v, axis, scatter_dimension=0,
                                    tiled=True)
        out = apply_op("c_reducescatter", impl, (src,), {})
        tensor._value = out._value
        return tensor
    tensor._value = src._value
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axis = current_axis(group)
    from ..ops.manipulation import stack
    x = (stack(in_tensor_list, axis=0)
         if isinstance(in_tensor_list, (list, tuple)) else in_tensor_list)
    if axis is not None:
        def impl(v):
            return lax.all_to_all(v, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        out = apply_op("c_alltoall", impl, (x,), {})
    else:
        out = x
    if isinstance(out_tensor_list, list):
        for i in range(out.shape[0]):
            out_tensor_list.append(out[i])
    return out


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P send (reference send_v2, pipeline edges). In SPMD this is a
    ppermute; exposed mainly for the pipeline schedule."""
    axis = current_axis(group)
    if axis is None:
        return tensor
    n = get_world_size()

    def impl(v):
        sz = (jax.lax.axis_size(axis) if hasattr(jax.lax, 'axis_size')
              else jax.lax.psum(1, axis))
        perm = [(i, (i + 1) % sz) for i in range(sz)]
        return lax.ppermute(v, axis, perm)
    out = apply_op("send_v2", impl, (tensor,), {})
    return out


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


def barrier(group=None):
    axis = current_axis(group)
    if axis is not None:
        one = Tensor(jnp.ones(()))
        all_reduce(one, group=group)
    return None


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor._value.block_until_ready()


# ---------------------------------------------------------------------------
# tensor-parallel `split` (reference `distributed/collective.py:566`)
# ---------------------------------------------------------------------------

def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Build a tensor-parallel layer (parallel embedding / row|col linear).
    TPU-native: returns a layer whose weights carry GSPMD partition specs
    over the 'mp' axis — forward code stays dense; XLA partitions it."""
    from .tensor_parallel import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
    elif operation == "linear" and axis == 0:
        layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                  bias_attr=bias_attr)
    elif operation == "linear":
        layer = ColumnParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     bias_attr=bias_attr,
                                     gather_output=gather_out)
    else:
        raise ValueError(f"unsupported split operation {operation}")
    return layer(x)
