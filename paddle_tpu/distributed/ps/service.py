"""PS server + client over a length-prefixed TCP protocol.

Reference: `paddle/fluid/distributed/service/brpc_ps_server.cc` /
`brpc_ps_client.cc` (brpc/protobuf RPC). Here: the table math is native
C++ (csrc/ps_core.cc); the transport is a threaded socket server speaking
a fixed binary frame — no brpc dependency, same request surface
(pull/push dense|sparse, barrier, save/load, shutdown).

Frame: [op:u8][table:u32][n_ids:u64][payload_len:u64][ids...][payload...]
Reply: [status:u8][payload_len:u64][payload...]
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional

import numpy as np

from .tables import DenseTable, SparseTable

__all__ = ["PsServer", "PsClient", "TableConfig"]

OP_PULL_DENSE = 1
OP_PUSH_DENSE = 2
OP_PULL_SPARSE = 3
OP_PUSH_SPARSE = 4
OP_BARRIER = 5
OP_SAVE = 6
OP_LOAD = 7
OP_STOP = 8
OP_SET_DENSE = 9

_HDR = struct.Struct("<BIQQ")
_REP = struct.Struct("<BQ")


class TableConfig:
    def __init__(self, table_id, kind, size=0, dim=0, rule="sgd", lr=0.01,
                 init_range=0.05, name=""):
        self.table_id = table_id
        self.kind = kind  # "dense" | "sparse"
        self.size = size
        self.dim = dim
        self.rule = rule
        self.lr = lr
        self.init_range = init_range
        self.name = name or f"table_{table_id}"


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class PsServer:
    """reference BrpcPsServer — one thread per connection; barrier counts
    workers (reference `table/barrier_table.cc`)."""

    def __init__(self, endpoint: str, tables: List[TableConfig],
                 n_workers: int = 1):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._tables: Dict[int, object] = {}
        for cfg in tables:
            if cfg.kind == "dense":
                self._tables[cfg.table_id] = DenseTable(cfg.size, cfg.rule,
                                                        cfg.lr)
            else:
                self._tables[cfg.table_id] = SparseTable(
                    cfg.dim, cfg.rule, cfg.lr, cfg.init_range)
        self._cfgs = {c.table_id: c for c in tables}
        self._n_workers = n_workers
        self._barrier_lock = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self):
        return self._addr[1]

    def start(self, block=False):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(self._addr)
        self._addr = self._sock.getsockname()
        self._sock.listen(128)
        if block:
            self._serve()
        else:
            self._thread = threading.Thread(target=self._serve, daemon=True)
            self._thread.start()
        return self

    def _serve(self):
        self._sock.settimeout(0.2)
        conns = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            conns.append(t)
        self._sock.close()

    def _handle(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                hdr = _recv_exact(conn, _HDR.size)
                op, table_id, n_ids, plen = _HDR.unpack(hdr)
                ids = np.frombuffer(_recv_exact(conn, n_ids * 8),
                                    dtype=np.int64) if n_ids else None
                payload = _recv_exact(conn, plen) if plen else b""
                reply = self._dispatch(op, table_id, ids, payload)
                conn.sendall(_REP.pack(0, len(reply)) + reply)
                if op == OP_STOP:
                    self._stop.set()
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _dispatch(self, op, table_id, ids, payload) -> bytes:
        t = self._tables.get(table_id)
        if op == OP_PULL_DENSE:
            return t.pull().tobytes()
        if op == OP_PUSH_DENSE:
            t.push(np.frombuffer(payload, dtype=np.float32))
            return b""
        if op == OP_SET_DENSE:
            t.set(np.frombuffer(payload, dtype=np.float32))
            return b""
        if op == OP_PULL_SPARSE:
            return t.pull(ids).tobytes()
        if op == OP_PUSH_SPARSE:
            t.push(ids, np.frombuffer(payload, dtype=np.float32))
            return b""
        if op == OP_BARRIER:
            with self._barrier_lock:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= self._n_workers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_lock.notify_all()
                else:
                    while gen == self._barrier_gen and not \
                            self._stop.is_set():
                        self._barrier_lock.wait(timeout=1.0)
            return b""
        if op == OP_SAVE:
            path = payload.decode()
            for tid, tab in self._tables.items():
                if isinstance(tab, SparseTable):
                    tab.save(f"{path}.table{tid}")
                else:
                    np.save(f"{path}.table{tid}.npy", tab.pull())
            return b""
        if op == OP_LOAD:
            path = payload.decode()
            import os
            for tid, tab in self._tables.items():
                if isinstance(tab, SparseTable):
                    if os.path.exists(f"{path}.table{tid}"):
                        tab.load(f"{path}.table{tid}")
                elif os.path.exists(f"{path}.table{tid}.npy"):
                    tab.set(np.load(f"{path}.table{tid}.npy"))
            return b""
        if op == OP_STOP:
            return b""
        raise ValueError(f"unknown op {op}")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3)


class PsClient:
    """reference BrpcPsClient: sync pull / push (async batching lives in
    communicator.py)."""

    def __init__(self, endpoints: List[str]):
        self._endpoints = endpoints
        self._socks: Dict[str, socket.socket] = {}
        self._lock = threading.Lock()

    def _sock(self, ep):
        if ep not in self._socks:
            host, port = ep.rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=30)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[ep] = s
        return self._socks[ep]

    def _call(self, ep, op, table_id, ids=None, payload=b""):
        with self._lock:
            s = self._sock(ep)
            n_ids = 0 if ids is None else ids.size
            s.sendall(_HDR.pack(op, table_id, n_ids, len(payload)))
            if ids is not None and ids.size:
                s.sendall(np.ascontiguousarray(ids, np.int64).tobytes())
            if payload:
                s.sendall(payload)
            status, plen = _REP.unpack(_recv_exact(s, _REP.size))
            data = _recv_exact(s, plen) if plen else b""
            if status != 0:
                raise RuntimeError("PS call failed")
            return data

    def _shard_ep(self, ids):
        """sparse ids are range-sharded over servers by hash."""
        n = len(self._endpoints)
        return (np.abs(ids) % n).astype(np.int64)

    def pull_dense(self, table_id, server=0):
        return np.frombuffer(
            self._call(self._endpoints[server], OP_PULL_DENSE, table_id),
            dtype=np.float32).copy()

    def push_dense(self, table_id, grad, server=0):
        self._call(self._endpoints[server], OP_PUSH_DENSE, table_id,
                   payload=np.ascontiguousarray(grad,
                                                np.float32).tobytes())

    def set_dense(self, table_id, vals, server=0):
        self._call(self._endpoints[server], OP_SET_DENSE, table_id,
                   payload=np.ascontiguousarray(vals,
                                                np.float32).tobytes())

    def pull_sparse(self, table_id, ids, dim):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = np.empty((ids.size, dim), dtype=np.float32)
        shard = self._shard_ep(ids)
        for s, ep in enumerate(self._endpoints):
            m = shard == s
            if not m.any():
                continue
            data = self._call(ep, OP_PULL_SPARSE, table_id, ids[m])
            out[m] = np.frombuffer(data, np.float32).reshape(-1, dim)
        return out

    def push_sparse(self, table_id, ids, grads):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, np.float32).reshape(ids.size, -1)
        shard = self._shard_ep(ids)
        for s, ep in enumerate(self._endpoints):
            m = shard == s
            if not m.any():
                continue
            self._call(ep, OP_PUSH_SPARSE, table_id, ids[m],
                       grads[m].tobytes())

    def barrier(self):
        for ep in self._endpoints:
            self._call(ep, OP_BARRIER, 0)

    def save(self, path):
        for ep in self._endpoints:
            self._call(ep, OP_SAVE, 0, payload=path.encode())

    def load(self, path):
        for ep in self._endpoints:
            self._call(ep, OP_LOAD, 0, payload=path.encode())

    def stop_server(self):
        for ep in self._endpoints:
            try:
                self._call(ep, OP_STOP, 0)
            except Exception:
                pass

    def close(self):
        for s in self._socks.values():
            try:
                s.close()
            except Exception:
                pass
        self._socks.clear()
