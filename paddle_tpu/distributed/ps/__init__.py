from .communicator import AsyncCommunicator, GeoCommunicator
from .host_embedding import HostEmbedding, make_host_embedding_step
from .runtime import DistributedEmbedding, TheOnePSRuntime, the_one_ps
from .service import PsClient, PsServer, TableConfig
from .tables import DenseTable, SparseTable, native_available
