"""ctypes bindings for the native PS table core (csrc/ps_core.cc;
reference `paddle/fluid/distributed/table/common_{dense,sparse}_table.cc`).
Auto-builds the shared library on first use if missing."""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

__all__ = ["DenseTable", "SparseTable", "native_available"]

_LIB: Optional[ctypes.CDLL] = None


def _csrc_dir():
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))  # .../paddle_tpu
    return os.path.join(os.path.dirname(pkg_root), "csrc")


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    so = os.path.join(_csrc_dir(), "libps_core.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", _csrc_dir(), "libps_core.so"],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(so)
    lib.dense_table_create.restype = ctypes.c_void_p
    lib.dense_table_create.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                       ctypes.c_float]
    lib.dense_table_destroy.argtypes = [ctypes.c_void_p]
    lib.dense_table_pull.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_int64]
    lib.dense_table_push.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_int64]
    lib.dense_table_set.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_float),
                                    ctypes.c_int64]
    lib.sparse_table_create.restype = ctypes.c_void_p
    lib.sparse_table_create.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                        ctypes.c_float, ctypes.c_float,
                                        ctypes.c_uint32]
    lib.sparse_table_destroy.argtypes = [ctypes.c_void_p]
    lib.sparse_table_pull.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.c_int64,
                                      ctypes.POINTER(ctypes.c_float)]
    lib.sparse_table_push.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.c_int64,
                                      ctypes.POINTER(ctypes.c_float)]
    lib.sparse_table_size.restype = ctypes.c_int64
    lib.sparse_table_size.argtypes = [ctypes.c_void_p]
    lib.sparse_table_save.restype = ctypes.c_int64
    lib.sparse_table_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.sparse_table_load.restype = ctypes.c_int64
    lib.sparse_table_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    _LIB = lib
    return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def _fp(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _ip(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


class DenseTable:
    def __init__(self, size: int, rule: str = "sgd", lr: float = 0.01):
        self.size = int(size)
        self._lib = _load()
        self._h = self._lib.dense_table_create(self.size, rule.encode(),
                                               float(lr))

    def pull(self) -> np.ndarray:
        out = np.empty(self.size, dtype=np.float32)
        self._lib.dense_table_pull(self._h, _fp(out), self.size)
        return out

    def push(self, grad: np.ndarray):
        g = np.ascontiguousarray(grad, dtype=np.float32).reshape(-1)
        self._lib.dense_table_push(self._h, _fp(g), g.size)

    def set(self, vals: np.ndarray):
        v = np.ascontiguousarray(vals, dtype=np.float32).reshape(-1)
        self._lib.dense_table_set(self._h, _fp(v), v.size)

    def __del__(self):
        try:
            self._lib.dense_table_destroy(self._h)
        except Exception:
            pass


class SparseTable:
    def __init__(self, dim: int, rule: str = "sgd", lr: float = 0.01,
                 init_range: float = 0.05, seed: int = 0):
        self.dim = int(dim)
        self._lib = _load()
        self._h = self._lib.sparse_table_create(self.dim, rule.encode(),
                                                float(lr), float(init_range),
                                                int(seed))

    def pull(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, dtype=np.int64).reshape(-1)
        out = np.empty((ids.size, self.dim), dtype=np.float32)
        self._lib.sparse_table_pull(self._h, _ip(ids), ids.size, _fp(out))
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray):
        ids = np.ascontiguousarray(ids, dtype=np.int64).reshape(-1)
        g = np.ascontiguousarray(grads, dtype=np.float32).reshape(
            ids.size, self.dim)
        self._lib.sparse_table_push(self._h, _ip(ids), ids.size, _fp(g))

    def __len__(self):
        return int(self._lib.sparse_table_size(self._h))

    def save(self, path: str) -> int:
        return int(self._lib.sparse_table_save(self._h, path.encode()))

    def load(self, path: str) -> int:
        return int(self._lib.sparse_table_load(self._h, path.encode()))

    def __del__(self):
        try:
            self._lib.sparse_table_destroy(self._h)
        except Exception:
            pass
