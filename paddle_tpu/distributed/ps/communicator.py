"""Async/Geo communicators (reference
`paddle/fluid/distributed/service/communicator.h:197/346/495` —
background threads merging sparse grads and pushing/pulling the tables).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional

import numpy as np

from .service import PsClient

__all__ = ["AsyncCommunicator", "GeoCommunicator"]


class AsyncCommunicator:
    """Batches pushes in a background thread; pulls are synchronous.
    reference AsyncCommunicator: send_queue + merge by id."""

    def __init__(self, client: PsClient, send_interval_s: float = 0.01,
                 merge_size: int = 16):
        self._client = client
        self._interval = send_interval_s
        self._merge_size = merge_size
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            batch = []
            try:
                batch.append(self._q.get(timeout=self._interval))
            except queue.Empty:
                continue
            while len(batch) < self._merge_size:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            self._flush(batch)
        # drain
        rest = []
        while True:
            try:
                rest.append(self._q.get_nowait())
            except queue.Empty:
                break
        if rest:
            self._flush(rest)

    def _flush(self, batch):
        # merge sparse grads by (table, id); sum dense grads per table
        sparse: Dict[int, Dict[int, np.ndarray]] = {}
        dense: Dict[int, np.ndarray] = {}
        for kind, table_id, a, b in batch:
            if kind == "sparse":
                d = sparse.setdefault(table_id, {})
                for i, g in zip(a.tolist(), b):
                    if i in d:
                        d[i] = d[i] + g
                    else:
                        d[i] = g.copy()
            else:
                dense[table_id] = (dense[table_id] + a
                                   if table_id in dense else a.copy())
        for tid, d in sparse.items():
            ids = np.fromiter(d.keys(), dtype=np.int64)
            grads = np.stack([d[i] for i in ids.tolist()])
            self._client.push_sparse(tid, ids, grads)
        for tid, g in dense.items():
            self._client.push_dense(tid, g)

    def push_sparse_async(self, table_id, ids, grads):
        self._q.put(("sparse", table_id, np.asarray(ids, np.int64),
                     np.asarray(grads, np.float32)))

    def push_dense_async(self, table_id, grad):
        self._q.put(("dense", table_id, np.asarray(grad, np.float32), None))

    def pull_sparse(self, table_id, ids, dim):
        return self._client.pull_sparse(table_id, ids, dim)

    def pull_dense(self, table_id):
        return self._client.pull_dense(table_id)

    def flush(self):
        while not self._q.empty():
            time.sleep(self._interval)
        time.sleep(2 * self._interval)

    def stop(self):
        self.flush()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3)


class GeoCommunicator(AsyncCommunicator):
    """Geo-SGD (reference GeoCommunicator:495): workers train on local
    replicas; every k steps the DELTA vs the last synced snapshot is
    pushed (rule='sum') and the fresh global value pulled."""

    def __init__(self, client: PsClient, k_steps: int = 10):
        super().__init__(client)
        self._k = k_steps
        self._step = 0
        self._snapshots: Dict[int, np.ndarray] = {}

    def register_dense(self, table_id, initial: np.ndarray):
        self._snapshots[table_id] = initial.astype(np.float32).copy()
        self._client.set_dense(table_id, initial)

    def maybe_sync_dense(self, table_id, local: np.ndarray):
        """Returns possibly-updated local values."""
        self._step += 1
        if self._step % self._k:
            return local
        snap = self._snapshots[table_id]
        delta = local.astype(np.float32) - snap
        self._client.push_dense(table_id, delta)  # rule must be 'sum'
        fresh = self._client.pull_dense(table_id)
        self._snapshots[table_id] = fresh.copy()
        return fresh
