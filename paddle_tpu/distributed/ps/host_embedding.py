"""Host-offload sparse embedding path — the TPU-native HeterPS.

Reference: `paddle/fluid/framework/fleet/heter_ps/heter_comm.h:50` +
`PSGPUTrainer` (`framework/trainer.h:283`): giant embedding tables live in
host RAM, the accelerator runs the dense math, and each step is
pull → device compute → grad push with the optimizer rule applied
table-side.

TPU redesign: the table is the native C++ sharded hash
(`csrc/ps_core.cc` via ctypes, the same core the PS service uses); the
dense model is ONE jit'd XLA program whose inputs include the pulled
embedding block and whose outputs include dLoss/dEmbedding, so the only
host↔device traffic per step is the deduplicated rows in and their
gradients out. Duplicate ids in a batch are deduplicated host-side and
their gradients segment-summed ON DEVICE before the push, which keeps
adagrad/adam table rules correct (one update per touched row per step).
"""
from __future__ import annotations

from typing import Callable, Tuple

import numpy as np

__all__ = ["HostEmbedding", "make_host_embedding_step"]


class HostEmbedding:
    """A host-RAM embedding table with dedup pull/push.

    dim: embedding width; rule: 'sgd' | 'adam' | 'sum' (applied in the
    C++ core on push); lr/init_range/seed as in SparseTable.
    """

    def __init__(self, dim: int, rule: str = "sgd", lr: float = 0.01,
                 init_range: float = 0.05, seed: int = 0):
        from .tables import SparseTable
        self.dim = int(dim)
        self.table = SparseTable(dim, rule=rule, lr=lr,
                                 init_range=init_range, seed=seed)

    def pull_dedup(self, ids) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """ids (any shape) → (rows [cap, dim], inverse [ids.size], uniq).

        rows are padded to the next power-of-two capacity: the unique
        count varies batch to batch, and an un-padded shape would retrace
        the jit'd device step every single step on TPU. Pad rows are
        zeros; their gradients are discarded at push time.
        """
        ids = np.ascontiguousarray(np.asarray(ids, np.int64)).reshape(-1)
        uniq, inverse = np.unique(ids, return_inverse=True)
        rows = self.table.pull(uniq)
        cap = 1 << max(0, int(uniq.size - 1)).bit_length()
        if cap > uniq.size:
            rows = np.concatenate(
                [rows, np.zeros((cap - uniq.size, self.dim), np.float32)])
        return rows, inverse.astype(np.int32), uniq

    def push(self, uniq_ids: np.ndarray, grads) -> None:
        self.table.push(np.asarray(uniq_ids, np.int64),
                        np.asarray(grads, np.float32))

    def __len__(self):
        return len(self.table)

    def save(self, path):
        return self.table.save(path)

    def load(self, path):
        return self.table.load(path)


def make_host_embedding_step(dense_layer, optimizer, loss_fn: Callable,
                             emb: HostEmbedding):
    """Build `step(ids, *data) -> loss` for a dense model over a host table.

    dense_layer(emb_batch, *data) -> outputs; loss_fn(outputs, *data) ->
    scalar Tensor. The dense parameters train through `optimizer` on
    device; the embedding rows train through the table rule on host —
    exactly the HeterPS split (`heter_comm.h:50`).
    """
    import jax
    import jax.numpy as jnp

    from ...framework.autograd import trace_mode
    from ...framework.functional import functionalize
    from ...framework.tensor import Tensor

    apply_fn, pv, bv = functionalize(dense_layer)
    opt_state = optimizer.init_state_pytree(pv)

    def loss_of(pv_, bv_, rng, rows, inverse, data):
        emb_batch = jnp.take(rows, inverse, axis=0)   # un-dedup on device
        out, new_bufs = apply_fn(pv_, bv_, rng, True, emb_batch, *data)
        with trace_mode():
            lv = loss_fn(jax.tree_util.tree_map(Tensor, out),
                         [Tensor(d) for d in data])
        lv = lv._value if isinstance(lv, Tensor) else lv
        return jnp.mean(lv.astype("float32")), new_bufs

    def device_step(pv_, bv_, opt_state_, step_no, lr, rng, rows, inverse,
                    *data):
        (lv, new_bufs), (gp, grows) = jax.value_and_grad(
            loss_of, argnums=(0, 3), has_aux=True)(
                pv_, bv_, rng, rows, inverse, data)
        new_pv, new_opt = optimizer.apply_gradients_pytree(
            gp, pv_, opt_state_, lr, step_no)
        # grows is already segment-summed over duplicates by the take-VJP
        return lv, grows, new_pv, new_bufs, new_opt

    jit_step = jax.jit(device_step)
    state = {"pv": pv, "bv": bv, "opt": opt_state, "n": 0}

    def step(ids, *data):
        from ...framework import random as frandom
        rows, inverse, uniq = emb.pull_dedup(ids)
        data = tuple(jnp.asarray(np.asarray(d)) for d in data)
        lv, grows, state["pv"], state["bv"], state["opt"] = jit_step(
            state["pv"], state["bv"], state["opt"],
            jnp.asarray(state["n"] + 1, "int32"),
            jnp.asarray(optimizer.get_lr(), "float32"),  # per-step, so LR
            frandom.get_rng_key(),                       # schedules work
            jnp.asarray(rows), jnp.asarray(inverse), *data)
        state["n"] += 1
        grows = np.asarray(jax.device_get(grows))
        emb.push(uniq, grows[:uniq.size])   # drop pad-row gradients
        return float(jax.device_get(lv))

    step.state = state
    return step
