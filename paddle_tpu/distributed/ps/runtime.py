"""PS runtime (reference `fleet/runtime/the_one_ps.py:399` TheOnePSRuntime:
_init_server/_init_worker/_run_server driving the C++ brpc service).

Here the server drives the native C++ tables (csrc/ps_core.cc) behind the
TCP service; workers get a client + async communicator. Role/topology come
from the same PADDLE_* env contract (role_maker)."""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from .communicator import AsyncCommunicator, GeoCommunicator
from .service import PsClient, PsServer, TableConfig

__all__ = ["TheOnePSRuntime", "the_one_ps", "DistributedEmbedding"]

_runtime: Optional["TheOnePSRuntime"] = None


def the_one_ps() -> "TheOnePSRuntime":
    global _runtime
    if _runtime is None:
        _runtime = TheOnePSRuntime()
    return _runtime


class TheOnePSRuntime:
    def __init__(self):
        self.server: Optional[PsServer] = None
        self.client: Optional[PsClient] = None
        self.communicator: Optional[AsyncCommunicator] = None
        self.tables: List[TableConfig] = []
        self._next_table_id = 0

    # -- configuration ------------------------------------------------------
    def register_sparse_table(self, dim, rule="sgd", lr=0.01,
                              init_range=0.05, name=""):
        cfg = TableConfig(self._next_table_id, "sparse", dim=dim, rule=rule,
                          lr=lr, init_range=init_range, name=name)
        self.tables.append(cfg)
        self._next_table_id += 1
        return cfg.table_id

    def register_dense_table(self, size, rule="sgd", lr=0.01, name=""):
        cfg = TableConfig(self._next_table_id, "dense", size=size, rule=rule,
                          lr=lr, name=name)
        self.tables.append(cfg)
        self._next_table_id += 1
        return cfg.table_id

    def _server_endpoints(self):
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        return [e for e in eps.split(",") if e] or ["127.0.0.1:0"]

    # -- lifecycle (fleet surface) -----------------------------------------
    def init_server(self, *args, **kwargs):
        idx = int(os.environ.get("PADDLE_PSERVER_ID",
                                 os.environ.get("POD_ID", "0")))
        eps = self._server_endpoints()
        n_workers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.server = PsServer(eps[min(idx, len(eps) - 1)], self.tables,
                               n_workers)
        self.server.start(block=False)
        return self.server

    def run_server(self):
        if self.server is None:
            self.init_server()
        # block until stopped
        if self.server._thread is not None:
            self.server._thread.join()

    def init_worker(self, geo_k: int = 0):
        self.client = PsClient(self._server_endpoints())
        if geo_k > 0:
            self.communicator = GeoCommunicator(self.client, geo_k).start()
        else:
            self.communicator = AsyncCommunicator(self.client).start()
        return self.client

    def stop_worker(self):
        if self.communicator is not None:
            self.communicator.stop()
        if self.client is not None:
            self.client.stop_server()
            self.client.close()


class DistributedEmbedding:
    """Worker-side sparse embedding over the PS (reference
    `operators/pscore/distributed_lookup_table_op` + CommonSparseTable):
    pull rows for the batch's ids, compute locally on TPU, push grads."""

    def __init__(self, runtime: TheOnePSRuntime, table_id: int, dim: int):
        self.rt = runtime
        self.table_id = table_id
        self.dim = dim
        self._last_ids = None

    def pull(self, ids: np.ndarray) -> np.ndarray:
        self._last_ids = np.asarray(ids, np.int64).reshape(-1)
        return self.rt.client.pull_sparse(self.table_id, self._last_ids,
                                          self.dim).reshape(
            *np.asarray(ids).shape, self.dim)

    def push_grad(self, grads: np.ndarray, async_=True):
        g = np.asarray(grads, np.float32).reshape(-1, self.dim)
        if async_ and self.rt.communicator is not None:
            self.rt.communicator.push_sparse_async(self.table_id,
                                                   self._last_ids, g)
        else:
            self.rt.client.push_sparse(self.table_id, self._last_ids, g)
