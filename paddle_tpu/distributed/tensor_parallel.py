"""Tensor-parallel layers (reference `distributed/collective.py:566` split:
parallel embedding, row/col-parallel Linear built from c_allreduce/c_concat
epilogues + `operators/collective/c_split_op` etc.).

TPU-native (GSPMD): the layer stores FULL (logical) weights annotated with
a PartitionSpec over the 'mp' mesh axis. Forward is the ordinary dense op
plus sharding constraints; XLA partitions the matmul and inserts the same
allreduce/allgather epilogues the reference hand-writes — but fused and
scheduled by the compiler over ICI. Megatron-style column→row pairs
therefore need NO explicit collectives in framework code.
"""
from __future__ import annotations

import jax
from jax.sharding import PartitionSpec

from ..framework.tensor import Tensor, apply_op
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import Layer
from ..parallel.mesh import get_mesh, named_sharding

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "mark_sharding", "constraint"]


def mark_sharding(param, *spec):
    """Attach a partition spec to a Parameter; consumed by the SPMD train
    step builder (parallel/api.py) when laying params onto the mesh."""
    param.partition_spec = PartitionSpec(*spec)
    return param


def constraint(x, *spec):
    """with_sharding_constraint on a framework Tensor (no-op off-mesh;
    axes absent from the current mesh are dropped so TP layers run
    unchanged on dp-only meshes)."""
    mesh = get_mesh()
    if mesh is None:
        return x
    spec = tuple(s if (s is None or (s in mesh.axis_names
                                     and mesh.shape[s] > 1)) else None
                 for s in spec)
    sh = named_sharding(*spec)

    def impl(v):
        return jax.lax.with_sharding_constraint(v, sh)
    return apply_op("sharding_constraint", impl, (x,), {})


class ColumnParallelLinear(Layer):
    """weight [in, out] sharded on out ('mp'); output optionally gathered."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, bias_attr=None, gather_output=True,
                 name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, None, "mp")
        if has_bias and bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)
            mark_sharding(self.bias, "mp")
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = constraint(out, None)  # force replicated (XLA all-gather)
        else:
            out = constraint(out, *([None] * (out.ndim - 1) + ["mp"]))
        return out


class RowParallelLinear(Layer):
    """weight [in, out] sharded on in ('mp'); XLA inserts the partial-sum
    allreduce the reference writes as c_allreduce_sum."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, bias_attr=None, input_is_parallel=False,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, "mp", None)
        if has_bias and bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = constraint(x, *([None] * (x.ndim - 1) + ["mp"]))
        out = F.linear(x, self.weight, self.bias)
        return constraint(out, None)


class VocabParallelEmbedding(Layer):
    """weight [vocab, emb] sharded on vocab ('mp') — GSPMD partitions the
    gather (reference: shard_index + c_embedding + allreduce)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, "mp", None)

    def forward(self, x):
        return F.embedding(x, self.weight)
