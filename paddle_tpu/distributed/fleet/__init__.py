from . import dataset, elastic, metrics
from .dataset import InMemoryDataset, MultiSlotDataGenerator, QueueDataset
from .elastic import ElasticManager, ElasticStatus, HeartbeatClient
from .device_worker import DownpourWorker
from .fleet_wrapper import FleetWrapper
from .fleet_base import Fleet, fleet
from .http_server import KVClient, KVServer
from .role_maker import PaddleCloudRoleMaker, Role, RoleMakerBase, UserDefinedRoleMaker
from .strategy import DistributedStrategy
from .utils import HDFSClient, LocalFS, UtilBase

# module-level facade functions (reference: `fleet` is used as a module)
init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
build_sharded_train_step = fleet.build_sharded_train_step
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
is_worker = fleet.is_worker
is_server = fleet.is_server
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
barrier_worker = fleet.barrier_worker
save_persistables = fleet.save_persistables


def worker_index():
    return fleet.worker_index
