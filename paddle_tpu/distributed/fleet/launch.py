"""Launcher (reference `fleet/launch.py:208` launch_collective / :260
launch_ps, `launch_utils.py:435,494` start_local_trainers).

TPU model: ONE process per host (SPMD spans local chips), so the launcher
spawns one worker per node entry — or per requested proc — wiring the same
PADDLE_* env contract plus JAX coordinator vars. Usage:
  python -m paddle_tpu.distributed.fleet.launch --nproc_per_node 1 train.py
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _parse():
    p = argparse.ArgumentParser("fleet launch")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--gpus", type=str, default=None,
                   help="parity alias; selects device count per proc")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("--elastic", action="store_true",
                   help="start a KV heartbeat monitor: ranks that die, "
                        "fail init, or stop beating fault the job (an "
                        "in-process deadlock needs the manual touch() "
                        "mode — see fleet/elastic.py)")
    p.add_argument("--elastic_timeout", type=float, default=30.0)
    p.add_argument("--elastic_grace", type=float, default=120.0,
                   help="seconds a rank may take to its FIRST beat "
                        "(jax/backend init is slow)")
    p.add_argument("--servers", type=str, default="")
    p.add_argument("--workers", type=str, default="")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _spawn_procs(args):
    ips = args.ips.split(",")
    nproc = args.nproc_per_node
    world = len(ips) * nproc
    endpoints = [f"{ip}:{args.started_port + i}"
                 for ip in ips for i in range(nproc)]
    os.makedirs(args.log_dir, exist_ok=True)
    kv_ep = None
    if getattr(args, "elastic", False):
        from .http_server import KVServer
        kv = KVServer().start()
        kv_ep = f"127.0.0.1:{kv.port}"
    procs = []
    # this launcher instance only starts local ranks (reference behavior)
    local_base = ips.index("127.0.0.1") * nproc if "127.0.0.1" in ips else 0
    coordinator = endpoints[0]
    for i in range(nproc):
        rank = local_base + i
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "JAX_COORDINATOR_ADDRESS": coordinator,
            "JAX_NUM_PROCESSES": str(world),
            "JAX_PROCESS_ID": str(rank),
            "TRAINING_ROLE": "TRAINER",
        })
        if kv_ep:
            env["PADDLE_ELASTIC_KV"] = kv_ep
        logf = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "w")
        cmd = [sys.executable, "-u", args.training_script] + \
            args.training_script_args
        procs.append((subprocess.Popen(cmd, env=env, stdout=logf,
                                       stderr=subprocess.STDOUT), logf,
                      rank))
    local_ranks = [r for _, _, r in procs]
    return procs, kv_ep, local_ranks


def _watch(procs):
    """reference `launch_utils.py:526 watch_local_trainers`: abort the job
    if any child dies."""
    try:
        while procs:
            alive = []
            for p, logf, rank in procs:
                ret = p.poll()
                if ret is None:
                    alive.append((p, logf, rank))
                elif ret != 0:
                    print(f"[fleet.launch] rank {rank} FAILED "
                          f"(exit {ret}); terminating job", file=sys.stderr)
                    for q, _, _ in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    sys.exit(ret)
            procs = alive
            time.sleep(1)
    except KeyboardInterrupt:
        for p, _, _ in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        raise


def launch():
    args = _parse()
    procs, kv_ep, local_ranks = _spawn_procs(args)
    if kv_ep:
        # liveness on top of the exit watchdog: a local rank that dies,
        # fails init, or stops beating faults the whole job. Only LOCAL
        # ranks are watched — the KV is loopback; each node's launcher
        # watches its own ranks (reference watch_local_trainers scope).
        from .elastic import ElasticManager

        def on_fault(dead):
            print(f"[fleet.launch] rank(s) {dead} stopped heartbeating; "
                  f"terminating job", file=sys.stderr)
            for p, _, _ in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
        ElasticManager(kv_ep, ranks=local_ranks,
                       timeout=args.elastic_timeout,
                       grace=args.elastic_grace).watch(on_fault=on_fault)
    _watch(procs)


main = launch

if __name__ == "__main__":
    launch()
