"""KV http server for rendezvous (reference `fleet/utils/http_server.py`
— the HTTP store behind gloo rendezvous in role_maker.py:33-200)."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import request as urlrequest

__all__ = ["KVServer", "KVClient"]


class _Handler(BaseHTTPRequestHandler):
    store = {}
    lock = threading.Lock()

    def log_message(self, *args):
        pass

    def do_GET(self):
        # self.store resolves through the per-server subclass (KVServer
        # builds one per instance) — never name _Handler here
        with self.lock:
            val = self.store.get(self.path)
        if val is None:
            self.send_response(404)
            self.end_headers()
        else:
            self.send_response(200)
            self.end_headers()
            self.wfile.write(val)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        with self.lock:
            self.store[self.path] = data
        self.send_response(200)
        self.end_headers()

    do_POST = do_PUT

    def do_DELETE(self):
        with self.lock:
            self.store.pop(self.path, None)
        self.send_response(200)
        self.end_headers()


class KVServer:
    def __init__(self, port=0, size=None):
        # per-instance store: two KV servers in one process (tests, PS +
        # elastic side by side) must not share keys
        handler = type("_KVHandler", (_Handler,),
                       {"store": {}, "lock": threading.Lock()})
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()

    def should_stop(self):
        return False


class KVClient:
    def __init__(self, endpoint):
        if not endpoint.startswith("http"):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")

    def get(self, key):
        try:
            with urlrequest.urlopen(f"{self.endpoint}/{key.lstrip('/')}",
                                    timeout=5) as r:
                return r.read().decode()
        except Exception:
            return None

    def put(self, key, value):
        req = urlrequest.Request(f"{self.endpoint}/{key.lstrip('/')}",
                                 data=str(value).encode(), method="PUT")
        try:
            urlrequest.urlopen(req, timeout=5)
            return True
        except Exception:
            return False

    def delete(self, key):
        req = urlrequest.Request(f"{self.endpoint}/{key.lstrip('/')}",
                                 method="DELETE")
        try:
            urlrequest.urlopen(req, timeout=5)
            return True
        except Exception:
            return False
