"""DistributedStrategy (reference
`fleet/base/distributed_strategy.py:104` + proto
`framework/distributed_strategy.proto:122`). Plain typed config — each
field maps onto a sharding/transform decision in the SPMD step builder
instead of a meta-optimizer program rewrite."""
from __future__ import annotations

__all__ = ["DistributedStrategy"]


class _Cfg(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # toggles (reference proto field names kept)
        self.amp = False
        self.amp_configs = _Cfg(init_loss_scaling=32768.0, use_pure_fp16=False,
                                custom_white_list=[], custom_black_list=[],
                                dtype="bfloat16")
        self.recompute = False
        self.recompute_configs = _Cfg(checkpoints=[])
        self.gradient_merge = False
        self.gradient_merge_configs = _Cfg(k_steps=1, avg=True)
        self.sharding = False
        self.sharding_configs = _Cfg(stage=1, fuse_broadcast_MB=32,
                                     hybrid_dp=False,
                                     sharding_degree=1)
        self.pipeline = False
        self.pipeline_configs = _Cfg(accumulate_steps=1, micro_batch_size=1)
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Cfg(tensor_parallel_degree=1)
        self.sequence_parallel = False
        self.sequence_parallel_configs = _Cfg(degree=1, impl="ring")
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.dgc_configs = _Cfg(momentum=0.9, sparsity=0.999,
                                rampup_step=1)
        self.localsgd = False
        self.localsgd_configs = _Cfg(k_steps=4, begin_step=1)
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = _Cfg(init_k_steps=4, begin_step=1)
        self.fp16_allreduce = False
        self.a_sync = False
        self.a_sync_configs = _Cfg(k_steps=0, geo=False)
        self.hierarchical_allreduce = False
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.last_comm_group_size_MB = 1
        self.fuse_all_reduce_ops = True

    # hybrid topology (modern fleet): degrees per mesh axis
    @property
    def hybrid_configs(self):
        return getattr(self, "_hybrid", None) or {
            "dp_degree": -1, "mp_degree": 1, "pp_degree": 1, "sp_degree": 1}

    @hybrid_configs.setter
    def hybrid_configs(self, cfg):
        base = {"dp_degree": -1, "mp_degree": 1, "pp_degree": 1,
                "sp_degree": 1}
        base.update(cfg or {})
        self._hybrid = base

    def mesh_axes(self, n_devices):
        """Resolve degrees into a mesh axes dict."""
        h = dict(self.hybrid_configs)
        if self.tensor_parallel:
            h["mp_degree"] = max(
                h.get("mp_degree", 1),
                self.tensor_parallel_configs.get("tensor_parallel_degree", 1))
        if self.pipeline:
            h["pp_degree"] = max(h.get("pp_degree", 1), 2)
        if self.sequence_parallel:
            h["sp_degree"] = max(
                h.get("sp_degree", 1),
                self.sequence_parallel_configs.get("degree", 1))
        axes = {}
        known = 1
        for name, key in (("mp", "mp_degree"), ("pp", "pp_degree"),
                          ("sp", "sp_degree")):
            d = int(h.get(key, 1) or 1)
            if d > 1:
                axes[name] = d
                known *= d
        dp = h.get("dp_degree", -1)
        axes["dp"] = (n_devices // known) if dp in (-1, None) else int(dp)
        return {"dp": axes.pop("dp"), **axes}

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
