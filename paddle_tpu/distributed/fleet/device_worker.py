"""Device workers — per-process training loops over a Dataset stream
(reference `paddle/fluid/framework/device_worker.h:148-637`:
HogwildWorker (dense, lock-free), DownpourWorker (PS sparse pull/push,
`downpour_worker.cc`), driven by MultiTrainer/DistMultiTrainer
(`framework/trainer.h:53`, `executor.cc:152` RunFromDataset).

TPU redesign: a "worker" is not a thread pinned to a card — SPMD covers
the chips — it is the HOST loop that marries the data stream to ONE jit'd
XLA step. HogwildWorker ≈ Executor.train_from_dataset (already present).
DownpourWorker here implements the PS recipe: per batch, pull the touched
sparse rows through FleetWrapper, run the fused device fwd/bwd, push
sparse grads (async) and dense grads, with the table applying the rule —
the same pull→compute→push dataflow as `downpour_worker.cc`, minus the
thread farm XLA makes unnecessary."""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

__all__ = ["DownpourWorker"]


class _PsTableView:
    """Adapts FleetWrapper pull/push to the HostEmbedding interface
    make_host_embedding_step programs against — the jit'd device kernel
    stays in ONE place (distributed/ps/host_embedding.py)."""

    def __init__(self, fw, table_id: int, dim: int, async_push: bool):
        self.fw = fw
        self.tid = table_id
        self.dim = dim
        self.async_push = async_push

    def pull_dedup(self, ids):
        ids = np.ascontiguousarray(np.asarray(ids, np.int64)).reshape(-1)
        uniq, inverse = np.unique(ids, return_inverse=True)
        rows = self.fw.pull_sparse_vars_sync(self.tid, uniq,
                                             fea_dim=self.dim)
        # pad to pow2 so varying unique counts don't retrace the step
        # (same policy as HostEmbedding.pull_dedup)
        cap = 1 << max(0, int(uniq.size - 1)).bit_length()
        if cap > uniq.size:
            rows = np.concatenate(
                [rows, np.zeros((cap - uniq.size, self.dim), np.float32)])
        return rows, inverse.astype(np.int32), uniq

    def push(self, uniq_ids, grads):
        if self.async_push:
            self.fw.push_sparse_vars_async(self.tid, uniq_ids, grads)
        else:
            self.fw._client.push_sparse(
                self.tid, np.asarray(uniq_ids, np.int64),
                np.asarray(grads, np.float32))


class DownpourWorker:
    """Train a dense head over PS-resident sparse embeddings.

    dense_layer(emb_flat, *batch_rest) -> out; loss_fn(out, batch) ->
    scalar Tensor. Batches yield (ids, *rest). Dense params train
    on-device through `optimizer`; sparse rows train table-side (async
    push, like the reference Downpour push queues)."""

    def __init__(self, fleet_wrapper, sparse_table_id: int, fea_dim: int,
                 dense_layer, optimizer, loss_fn: Callable,
                 async_push: bool = True):
        from ..ps.host_embedding import make_host_embedding_step
        self.fw = fleet_wrapper
        self._view = _PsTableView(fleet_wrapper, sparse_table_id, fea_dim,
                                  async_push)
        self._step = make_host_embedding_step(dense_layer, optimizer,
                                              loss_fn, self._view)

    def train_one_batch(self, ids, *data) -> float:
        return self._step(ids, *data)

    def train_from_dataset(self, dataset, epochs: int = 1,
                           flush_every: Optional[int] = None):
        """reference Executor::RunFromDataset + DownpourWorker::TrainFiles.
        dataset yields (ids, *rest) batches."""
        losses = []
        for _ in range(epochs):
            for i, batch in enumerate(dataset):
                losses.append(self.train_one_batch(*batch))
                if flush_every and (i + 1) % flush_every == 0:
                    self.fw.client_flush()
        self.fw.client_flush()
        return losses
