"""Elastic training / failure detection (reference
`python/paddle/distributed/fleet/elastic/manager.py`: ElasticManager with
etcd-backed node heartbeats, `launch_utils.py:526 watch_local_trainers`,
and the PS barrier-table liveness of `table/barrier_table.cc`).

TPU redesign: heartbeats ride the fleet KV http server (no etcd in the
image) — every rank PUTs `beat/<rank>` on a cadence; the master scans
staleness and flips the job state to FAULT when a rank misses
`timeout` seconds, at which point launchers restart ranks from the last
auto-checkpoint (incubate/checkpoint.py). The scale decision (restart vs
proceed with fewer ranks) mirrors the reference's
ELASTIC_FAULT_TOLERANC(E) levels."""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional
from urllib import request as _rq

__all__ = ["ElasticManager", "HeartbeatClient", "ElasticStatus"]


class ElasticStatus:
    OK = "ok"
    FAULT = "fault"
    EXIT = "exit"


def _http(method, url, data=b""):
    req = _rq.Request(url, data=data if method == "PUT" else None,
                      method=method)
    with _rq.urlopen(req, timeout=5) as r:
        return r.read()


class HeartbeatClient:
    """Runs inside each rank: PUT beat/<rank> every `interval` seconds.

    Liveness granularity: the beat runs on a background thread, so it
    proves the PROCESS is alive (crash, OOM-kill, lost host, failed
    init), not that the training loop is making progress — an in-process
    deadlock keeps beating. For loop-level liveness pass `manual=True`
    and call `touch()` from the train loop; beats then stop the moment
    the loop stops. A clean exit writes `exit/<rank>` (atexit) so the
    master can tell completion from death."""

    def __init__(self, kv_endpoint: str, rank: int, interval: float = 2.0,
                 manual: bool = False):
        self.kv = kv_endpoint
        self.url = f"http://{kv_endpoint}/beat/{rank}"
        self.rank = rank
        self.interval = interval
        self.manual = manual
        self._touched = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat_once(self):
        _http("PUT", self.url, str(time.time()).encode())

    def touch(self):
        """Mark loop progress (manual mode): the next tick beats only if
        touched since the last one."""
        self._touched.set()

    def mark_exited(self):
        try:
            _http("PUT", f"http://{self.kv}/exit/{self.rank}", b"0")
        except Exception:
            pass

    def start(self):
        try:
            self.beat_once()   # synchronous first beat: no startup race
        except Exception:
            pass
        import atexit
        atexit.register(self.mark_exited)

        def loop():
            while not self._stop.is_set():
                self._stop.wait(self.interval)
                if self.manual and not self._touched.is_set():
                    continue   # loop made no progress → no beat
                self._touched.clear()
                try:
                    self.beat_once()
                except Exception:
                    pass  # the MASTER decides liveness, not the worker
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, exited: bool = False):
        self._stop.set()
        if exited:
            self.mark_exited()
        if self._thread:
            self._thread.join(timeout=5)


class ElasticManager:
    """Runs on the master: watches rank heartbeats in the KV store and
    exposes the job state (reference ElasticManager._monitor)."""

    def __init__(self, kv_endpoint: str, world_size: int = None,
                 timeout: float = 10.0, grace: Optional[float] = None,
                 ranks=None):
        self.kv = kv_endpoint
        # watch only `ranks` when given: a loopback KV can only ever see
        # the LOCAL ranks' beats (multi-node launchers each watch theirs)
        self.ranks = list(ranks) if ranks is not None else \
            list(range(world_size or 1))
        self.world = len(self.ranks)
        self.timeout = timeout
        # ranks that never beat yet are given `grace` seconds from manager
        # start (jax/backend init can take tens of seconds)
        self.grace = timeout if grace is None else grace
        self._t0 = time.time()
        self._last: Dict[int, float] = {}
        self._status = ElasticStatus.OK
        self._dead: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _read(self, path) -> Optional[bytes]:
        try:
            return _http("GET", f"http://{self.kv}/{path}")
        except Exception:
            return None

    def _read_beat(self, rank) -> Optional[float]:
        raw = self._read(f"beat/{rank}")
        try:
            return float(raw.decode()) if raw is not None else None
        except Exception:
            return None

    def scan(self, now: Optional[float] = None) -> str:
        """One liveness sweep; returns the job status."""
        now = now if now is not None else time.time()
        dead, exited = [], []
        for r in self.ranks:
            if self._read(f"exit/{r}") is not None:
                exited.append(r)    # clean completion, not a fault
                continue
            beat = self._read_beat(r)
            if beat is not None:
                self._last[r] = beat
            seen = self._last.get(r)
            if seen is None:
                if now - self._t0 > self.grace:
                    dead.append(r)
            elif now - seen > self.timeout:
                dead.append(r)
        self._dead = dead
        if dead:
            self._status = ElasticStatus.FAULT
        elif len(exited) == len(self.ranks):
            self._status = ElasticStatus.EXIT
        else:
            self._status = ElasticStatus.OK
        return self._status

    @property
    def status(self):
        return self._status

    @property
    def dead_ranks(self):
        return list(self._dead)

    def watch(self, interval: float = 2.0, on_fault=None):
        """Background monitor; on_fault(dead_ranks) fires on transition
        to FAULT (reference: triggers job restart from checkpoint)."""
        def loop():
            was_ok = True
            while not self._stop.is_set():
                st = self.scan()
                if st == ElasticStatus.EXIT:
                    return          # whole job completed cleanly
                if st == ElasticStatus.FAULT and was_ok:
                    was_ok = False
                    if on_fault:
                        try:
                            on_fault(self.dead_ranks)
                        except Exception:
                            pass
                elif st == ElasticStatus.OK:
                    was_ok = True
                self._stop.wait(interval)
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
