"""Fleet metrics (reference `fleet/metrics/metric.py`: sum/max/min/auc/mae/
rmse aggregated across workers with allreduce). Single-host: local values;
multi-host: process_allgather over the jax distributed runtime."""
from __future__ import annotations

import numpy as np

__all__ = ["sum", "max", "min", "mean", "auc", "mae", "rmse", "acc"]


def _gather(value):
    arr = np.asarray(value, dtype=np.float64)
    try:
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(arr))
    except Exception:
        pass
    return arr[None]


def sum(input, scope=None, util=None):
    from ..ps import runtime  # noqa: F401 (parity import)
    return _gather(input).sum(0)


def max(input, scope=None, util=None):
    return _gather(input).max(0)


def min(input, scope=None, util=None):
    return _gather(input).min(0)


def mean(input, scope=None, util=None):
    return _gather(input).mean(0)


def acc(correct, total, scope=None, util=None):
    c = _gather(correct).sum()
    t = _gather(total).sum()
    return float(c) / float(np.maximum(t, 1))


def mae(abserr, total_ins_num, scope=None, util=None):
    return float(_gather(abserr).sum() / np.maximum(
        _gather(total_ins_num).sum(), 1))


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    return float(np.sqrt(_gather(sqrerr).sum() / np.maximum(
        _gather(total_ins_num).sum(), 1)))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from per-worker threshold histograms (reference
    fleet.metrics.auc)."""
    pos = _gather(stat_pos).sum(0)
    neg = _gather(stat_neg).sum(0)
    tot_pos, tot_neg = pos.sum(), neg.sum()
    if tot_pos == 0 or tot_neg == 0:
        return 0.0
    tpr = np.cumsum(pos[::-1]) / tot_pos
    fpr = np.cumsum(neg[::-1]) / tot_neg
    return float(np.trapezoid(tpr, fpr))
