"""Fleet facade (reference `fleet/base/fleet_base.py:63` Fleet, :130 init,
:598 distributed_optimizer, :643 distributed_model, :1070 minimize; the
meta-optimizer chain `fleet/meta_optimizers/*`).

TPU-native: instead of ranking meta-optimizers that rewrite Programs,
fleet.init builds the hybrid mesh from DistributedStrategy degrees, and
distributed_optimizer/distributed_model return thin wrappers that route
training through `parallel.spmd.make_sharded_train_step` — AMP = bf16
autocast in the traced step, recompute = jax.checkpoint, sharding = ZeRO
opt-state shardings, TP = GSPMD param specs, DP = batch-axis sharding.
One compiled program replaces the whole strategy-compiler pipeline.
"""
from __future__ import annotations

from typing import Optional

import jax

from ...framework.tensor import Tensor
from ...parallel.mesh import create_mesh, get_mesh
from ..env import get_rank, get_world_size, init_parallel_env
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase
from .strategy import DistributedStrategy

__all__ = ["Fleet", "fleet"]


class _DistributedOptimizer:
    """Wraps the user optimizer; carries the strategy into the train step
    (reference: the composed meta-optimizer chain)."""

    def __init__(self, optimizer, strategy, fleet_obj):
        self.user_defined_optimizer = optimizer
        self.user_defined_strategy = strategy
        self._fleet = fleet_obj

    def __getattr__(self, name):
        return getattr(self.user_defined_optimizer, name)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self.user_defined_optimizer.minimize(
            loss, startup_program, parameters, no_grad_set)

    def step(self):
        return self.user_defined_optimizer.step()

    def clear_grad(self):
        return self.user_defined_optimizer.clear_grad()


class _DistributedModel:
    """reference `fleet_base.py:643` distributed_model → DataParallel.
    Under SPMD, forward is unchanged (sharding annotations do the work);
    this wrapper exists for API parity and to build sharded train steps."""

    def __init__(self, layer, fleet_obj):
        self._layers = layer
        self._fleet = fleet_obj

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._is_collective = True
        self._inited = False

    # -- lifecycle ----------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._is_collective = is_collective
        self._strategy = strategy or DistributedStrategy()
        init_parallel_env()
        axes = self._strategy.mesh_axes(len(jax.devices()))
        create_mesh(axes)
        self._inited = True
        return self

    @property
    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_first_worker(self):
        return get_rank() == 0

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints() if self._role_maker \
            else ["127.0.0.1:6170"]
        return ",".join(eps) if to_string else eps

    def is_worker(self):
        return True

    def is_server(self):
        return (self._role_maker is not None
                and getattr(self._role_maker, "_is_server", False))

    def barrier_worker(self):
        pass

    # -- training -----------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        return _DistributedOptimizer(optimizer, self._strategy, self)

    def distributed_model(self, model):
        from ...parallel.spmd import shard_params
        if get_mesh() is not None:
            shard_params(model)
        return _DistributedModel(model, self)

    def batch_placement(self):
        """Per-leaf placement callable for io.DeviceFeeder, consistent
        with the sharding the strategy's train step expects (batch axis 0
        over 'dp', sequence axis over 'sp' when sequence_parallel is on).
        None when no mesh is live."""
        from ...parallel.spmd import batch_placement
        if get_mesh() is None:
            return None
        st = self._strategy or DistributedStrategy()
        return batch_placement(
            get_mesh(),
            sp_axis="sp" if getattr(st, "sequence_parallel", False)
            else None)

    def build_sharded_train_step(self, layer, optimizer, loss_fn,
                                 donate=True):
        """The heart: strategy → one compiled SPMD step (see module doc)."""
        from ...parallel.spmd import make_sharded_train_step
        st = self._strategy or DistributedStrategy()
        opt = getattr(optimizer, "user_defined_optimizer", optimizer)
        if st.pipeline:
            from ...parallel.pipeline import make_pipeline_train_step
            n_micro = int(st.pipeline_configs.get("accumulate_steps", 1))
            return make_pipeline_train_step(
                layer, opt, loss_fn, n_micro=max(n_micro, 1),
                mesh=get_mesh(), recompute=st.recompute)
        if st.localsgd or st.adaptive_localsgd:
            from ...parallel.localsgd import make_local_train_step
            cfg = (st.adaptive_localsgd_configs if st.adaptive_localsgd
                   else st.localsgd_configs)
            return make_local_train_step(
                layer, opt, loss_fn, mesh=get_mesh(),
                k_steps=cfg.get("init_k_steps", cfg.get("k_steps", 4)),
                begin_step=cfg.get("begin_step", 1),
                adaptive=st.adaptive_localsgd)
        return make_sharded_train_step(
            layer, opt, loss_fn, mesh=get_mesh(), donate=donate,
            zero_stage=(st.sharding_configs.get("stage", 1)
                        if st.sharding else 0),
            sp_axis="sp" if st.sequence_parallel else None,
            recompute=st.recompute,
            grad_dtype=("float16" if st.fp16_allreduce else None),
            dgc=st.dgc,
            dgc_momentum=st.dgc_configs.get("momentum", 0.9),
            dgc_sparsity=st.dgc_configs.get("sparsity", 0.999))

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        return [], []

    # -- PS-mode parity surface (full PS runtime in distributed/ps) --------
    def init_worker(self):
        from ..ps.runtime import the_one_ps
        the_one_ps().init_worker()

    def init_server(self, *args, **kwargs):
        from ..ps.runtime import the_one_ps
        the_one_ps().init_server(*args, **kwargs)

    def run_server(self):
        from ..ps.runtime import the_one_ps
        the_one_ps().run_server()

    def stop_worker(self):
        from ..ps.runtime import the_one_ps
        the_one_ps().stop_worker()

    def save_persistables(self, executor=None, dirname=None, main_program=None,
                          mode=0):
        from ...framework.io_state import save
        if dirname:
            import os
            os.makedirs(dirname, exist_ok=True)
            save({}, os.path.join(dirname, "fleet_persistables.pdparams"))

    @property
    def util(self):
        from .utils import UtilBase
        return UtilBase()


fleet = Fleet()
