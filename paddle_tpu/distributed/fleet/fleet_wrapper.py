"""FleetWrapper — the PSLib bridge surface (reference
`paddle/fluid/framework/fleet/fleet_wrapper.h`: PullSparseVarsSync /
PushSparseVarsWithLabelAsync / PullDenseVarsSync / PushDenseVarsAsync /
InitServer/InitWorker/StopServer/SaveModel..., the API Downpour device
workers program against).

TPU redesign: the external PSLib is replaced by this framework's own PS —
the native C++ table core behind the TCP service (`distributed/ps/`) —
so the wrapper is a thin veneer mapping the reference method names onto
PsServer/PsClient. Async pushes ride ONE background queue thread (the
client serializes requests anyway), copy their buffers (the trainer may
reuse its grad buffer immediately), and surface worker errors at
client_flush()/save_model() time like the reference's queue drain."""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["FleetWrapper"]


class FleetWrapper:
    _instance = None

    @classmethod
    def instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self._server = None
        self._client = None
        self._dims: Dict[int, int] = {}
        self._q: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._errors: List[BaseException] = []

    # -- lifecycle (reference InitServer/InitWorker/StopServer) ------------
    def init_server(self, endpoint: str, table_configs, n_workers=1):
        from ..ps.service import PsServer
        self._server = PsServer(endpoint, table_configs,
                                n_workers=n_workers).start()
        for cfg in table_configs:
            if cfg.kind == "sparse":
                self._dims[cfg.table_id] = cfg.dim
        host = endpoint.rsplit(":", 1)[0]
        return f"{host}:{self._server.port}"

    def init_worker(self, endpoints: List[str],
                    sparse_dims: Optional[Dict[int, int]] = None):
        """sparse_dims: table_id → embedding dim. Required on worker-only
        processes (the reference passes fea_dim per call instead)."""
        from ..ps.service import PsClient
        self._client = PsClient(endpoints)
        if sparse_dims:
            self._dims.update(sparse_dims)

        def drain():
            while True:
                item = self._q.get()
                if item is None:
                    return
                fn, args = item
                try:
                    fn(*args)
                except BaseException as e:  # surfaced at flush time
                    self._errors.append(e)
                finally:
                    self._q.task_done()
        self._worker = threading.Thread(target=drain, daemon=True)
        self._worker.start()

    def stop_server(self):
        if self._client:
            self.client_flush()
            self._q.put(None)
            try:
                self._client.stop_server()
            except Exception:
                pass
            self._client.close()
        if self._server:
            self._server.stop()

    # -- sparse (reference PullSparseVarsSync / PushSparseVarsAsync) -------
    def pull_sparse_vars_sync(self, table_id: int, ids,
                              fea_dim: Optional[int] = None) -> np.ndarray:
        dim = fea_dim if fea_dim is not None else self._dims.get(table_id)
        if dim is None:
            raise ValueError(
                f"unknown dim for sparse table {table_id}; pass fea_dim "
                f"or init_worker(..., sparse_dims={{...}})")
        ids = np.asarray(ids, np.int64).reshape(-1)
        return self._client.pull_sparse(table_id, ids, dim)

    def push_sparse_vars_async(self, table_id: int, ids, grads):
        ids = np.array(ids, np.int64, copy=True).reshape(-1)
        g = np.array(grads, np.float32, copy=True).reshape(ids.size, -1)
        self._q.put((self._client.push_sparse, (table_id, ids, g)))

    def push_sparse_vars_with_label_async(self, table_id, ids, grads,
                                          labels=None):
        """reference PushSparseVarsWithLabelAsync: labels feed PSLib's
        show/click accumulators, which our tables don't keep — accepted
        and ignored."""
        self.push_sparse_vars_async(table_id, ids, grads)

    # -- dense (reference PullDenseVarsSync / PushDenseVarsAsync) ----------
    def pull_dense_vars_sync(self, table_id: int, server=0) -> np.ndarray:
        return self._client.pull_dense(table_id, server=server)

    def push_dense_vars_async(self, table_id: int, grad, server=0):
        g = np.array(grad, np.float32, copy=True).reshape(-1)
        self._q.put((lambda t, gg, s: self._client.push_dense(
            t, gg, server=s), (table_id, g, server)))

    def client_flush(self, timeout: float = 60.0):
        """reference ClientFlush: drain the async push queue; raises the
        first worker error so a later save_model can't silently persist a
        state with pushes missing."""
        import time
        deadline = time.time() + timeout
        while not self._q.empty() and time.time() < deadline:
            time.sleep(0.01)
        self._q.join()
        if self._errors:
            err = self._errors[0]
            self._errors = []
            raise RuntimeError(f"async push failed: {err!r}") from err

    def barrier(self):
        self._client.barrier()

    # -- persistence (reference SaveModel/LoadModel/ShrinkSparseTable) -----
    def save_model(self, path: str, mode=0):
        self.client_flush()
        return self._client.save(path)

    def load_model(self, path: str, mode=0):
        return self._client.load(path)
