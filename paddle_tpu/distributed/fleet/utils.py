"""Fleet utils (reference `fleet/base/util_factory.py` UtilBase,
`fleet/utils/fs.py` HDFSClient/LocalFS, `fleet/utils/http_server.py`)."""
from __future__ import annotations

import os
import shutil
import subprocess

import numpy as np

__all__ = ["UtilBase", "LocalFS", "HDFSClient"]


class UtilBase:
    def all_reduce(self, input, mode="sum", comm_world="worker"):
        # single-host collective world: identity; multi-host rides jax
        arr = np.asarray(input)
        try:
            import jax
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                out = multihost_utils.process_allgather(arr)
                if mode == "sum":
                    return out.sum(0)
                if mode == "max":
                    return out.max(0)
                return out.min(0)
        except Exception:
            pass
        return arr

    def barrier(self, comm_world="worker"):
        try:
            import jax
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("fleet_util_barrier")
        except Exception:
            pass

    def all_gather(self, input, comm_world="worker"):
        return [input]

    def get_file_shard(self, files):
        from ..env import get_rank, get_world_size
        n, r = get_world_size(), get_rank()
        return sorted(files)[r::n]

    def print_on_rank(self, message, rank_id=0):
        from ..env import get_rank
        if get_rank() == rank_id:
            print(message)


class LocalFS:
    """reference `fleet/utils/fs.py` LocalFS."""

    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for e in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        open(path, "a").close()

    def cat(self, path):
        with open(path) as f:
            return f.read()

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient:
    """Shell-out HDFS client (reference `fs.py` HDFSClient). Degrades to
    LocalFS when the hadoop binary is unavailable (this offline image)."""

    def __init__(self, hadoop_home=None, configs=None):
        self._hadoop = None
        if hadoop_home:
            cand = os.path.join(hadoop_home, "bin", "hadoop")
            if os.path.exists(cand):
                self._hadoop = cand
        self._local = LocalFS()

    def _run(self, *args):
        cmd = [self._hadoop, "fs"] + list(args)
        return subprocess.run(cmd, capture_output=True, text=True)

    def is_exist(self, path):
        if self._hadoop is None:
            return self._local.is_exist(path)
        return self._run("-test", "-e", path).returncode == 0

    def makedirs(self, path):
        if self._hadoop is None:
            return self._local.mkdirs(path)
        self._run("-mkdir", "-p", path)

    mkdirs = makedirs

    def delete(self, path):
        if self._hadoop is None:
            return self._local.delete(path)
        self._run("-rm", "-r", path)

    def upload(self, local, remote):
        if self._hadoop is None:
            return self._local.upload(local, remote)
        self._run("-put", local, remote)

    def download(self, remote, local):
        if self._hadoop is None:
            return self._local.download(remote, local)
        self._run("-get", remote, local)

    def ls_dir(self, path):
        if self._hadoop is None:
            return self._local.ls_dir(path)
        out = self._run("-ls", path).stdout.splitlines()
        files = [l.split()[-1] for l in out if l.startswith("-")]
        dirs = [l.split()[-1] for l in out if l.startswith("d")]
        return dirs, files
