"""Role makers (reference `fleet/base/role_maker.py:357` RoleMakerBase /
`:528` PaddleCloudRoleMaker — env-var based cluster topology)."""
from __future__ import annotations

import os

__all__ = ["RoleMakerBase", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "Role"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role = Role.WORKER
        self._current_id = 0

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    @property
    def _is_server(self):
        return self.is_server()

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints or ["127.0.0.1:6170"]

    def get_pserver_endpoints(self):
        return self._server_endpoints


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the same env contract the reference launcher writes:
    PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS / PADDLE_PSERVERS_IP_PORT_LIST
    / TRAINING_ROLE."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._generate_role()

    def _generate_role(self):
        self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        ps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in ps.split(",") if e]
        role = os.environ.get("TRAINING_ROLE", "TRAINER")
        if role == "PSERVER":
            self._role = Role.SERVER
            self._current_id = int(os.environ.get("PADDLE_PORT_ID",
                                                  os.environ.get(
                                                      "POD_ID", "0")))
        else:
            self._role = Role.WORKER

    def _get_pserver_endpoints(self):
        return self._server_endpoints


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False, init_gloo=False, path=None,
                 current_id=0, role=Role.WORKER, worker_endpoints=None,
                 server_endpoints=None, worker_num=None, **kwargs):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = worker_endpoints or []
        self._server_endpoints = server_endpoints or []
