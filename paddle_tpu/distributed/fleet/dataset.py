"""Industrial dataset pipeline (reference `fleet/dataset/dataset.py`
InMemoryDataset/QueueDataset configuring C++ `framework/data_feed.cc`
MultiSlotDataFeed:664 + `data_set.cc` DatasetImpl LoadIntoMemory/
LocalShuffle/GlobalShuffle; user ETL via
`fleet/data_generator/data_generator.py` MultiSlotDataGenerator).

TPU-native: slot files are parsed by the native C++ parser
(csrc/data_feed.cc via ctypes), held in memory as packed arrays,
shuffled locally (global shuffle = exchange via the PS barrier in
multi-host jobs), and batched into dense int64/float32 arrays.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["InMemoryDataset", "QueueDataset", "MultiSlotDataGenerator"]

_LIB = None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    d = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))), "csrc")
    so = os.path.join(d, "libdata_feed.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", d, "libdata_feed.so"], check=True,
                       capture_output=True)
    lib = ctypes.CDLL(so)
    lib.data_feed_parse.restype = ctypes.c_void_p
    lib.data_feed_parse.argtypes = [ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_int),
                                    ctypes.c_int]
    lib.data_feed_n_lines.restype = ctypes.c_int64
    lib.data_feed_n_lines.argtypes = [ctypes.c_void_p]
    lib.data_feed_slot_size.restype = ctypes.c_int64
    lib.data_feed_slot_size.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int]
    for name, ptr in (("data_feed_copy_int", ctypes.c_int64),
                      ("data_feed_copy_float", ctypes.c_float),
                      ("data_feed_copy_lengths", ctypes.c_int64)):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ptr)]
    lib.data_feed_destroy.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


class _Slot:
    def __init__(self, name, dtype):
        self.name = name
        self.dtype = dtype  # "int64" | "float32"


class InMemoryDataset:
    """reference InMemoryDataset: set_use_var/set_batch_size/
    load_into_memory/local_shuffle → iterate batches."""

    def __init__(self):
        self._slots: List[_Slot] = []
        self._batch_size = 1
        self._files: List[str] = []
        self._records: Optional[list] = None
        self._thread_num = 1

    def init(self, batch_size=1, use_var=None, thread_num=1, **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        if use_var:
            self.set_use_var(use_var)

    def set_use_var(self, slots):
        self._slots = []
        for s in slots:
            if hasattr(s, "dtype"):
                dt = "float32" if "float" in str(s.dtype) else "int64"
                self._slots.append(_Slot(getattr(s, "name", "slot"), dt))
            elif isinstance(s, tuple):
                self._slots.append(_Slot(s[0], s[1]))
            else:
                self._slots.append(_Slot(str(s), "int64"))

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, n):
        self._thread_num = n

    def set_filelist(self, files):
        self._files = list(files)

    def load_into_memory(self):
        lib = _load()
        schema = (ctypes.c_int * len(self._slots))(
            *[0 if s.dtype == "int64" else 1 for s in self._slots])
        self._records = []
        for path in self._files:
            h = lib.data_feed_parse(path.encode(), schema, len(self._slots))
            if not h:
                raise FileNotFoundError(path)
            n = lib.data_feed_n_lines(h)
            per_slot = []
            for si, s in enumerate(self._slots):
                is_f = 1 if s.dtype == "float32" else 0
                total = lib.data_feed_slot_size(h, si, is_f)
                lens = np.empty(n, np.int64)
                lib.data_feed_copy_lengths(
                    h, si, lens.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int64)))
                if is_f:
                    vals = np.empty(total, np.float32)
                    lib.data_feed_copy_float(
                        h, si, vals.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_float)))
                else:
                    vals = np.empty(total, np.int64)
                    lib.data_feed_copy_int(
                        h, si, vals.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_int64)))
                offs = np.concatenate([[0], np.cumsum(lens)])
                per_slot.append((vals, offs))
            lib.data_feed_destroy(h)
            for i in range(n):
                rec = tuple(vals[offs[i]:offs[i + 1]]
                            for vals, offs in per_slot)
                self._records.append(rec)

    def local_shuffle(self):
        import random
        random.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=None):
        # single-host: same as local (reference exchanges via PS)
        self.local_shuffle()

    def release_memory(self):
        self._records = None

    def get_memory_data_size(self, fleet=None):
        return len(self._records or [])

    def __iter__(self):
        """Yield padded dense batches: per slot [B, max_len] (int64) or
        [B, max_len] float32 plus a length array."""
        recs = self._records or []
        for i in range(0, len(recs), self._batch_size):
            chunk = recs[i:i + self._batch_size]
            batch = []
            for si, s in enumerate(self._slots):
                rows = [r[si] for r in chunk]
                ml = max((len(r) for r in rows), default=1) or 1
                dt = np.int64 if s.dtype == "int64" else np.float32
                arr = np.zeros((len(rows), ml), dt)
                for j, r in enumerate(rows):
                    arr[j, :len(r)] = r
                batch.append(arr)
            yield tuple(batch)


class QueueDataset(InMemoryDataset):
    """Streaming variant: parses per-file lazily."""

    def load_into_memory(self):
        pass

    def __iter__(self):
        for f in self._files:
            self._records = None
            files, self._files = self._files, [f]
            try:
                InMemoryDataset.load_into_memory(self)
                yield from InMemoryDataset.__iter__(self)
            finally:
                self._files = files


class MultiSlotDataGenerator:
    """reference `data_generator.py:278`: user overrides generate_sample;
    run_from_stdin/_from_files writes the slot text format the C++ parser
    reads."""

    def generate_sample(self, line):
        raise NotImplementedError

    def _format(self, sample) -> str:
        parts = []
        for _name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def run_from_files(self, in_files: Sequence[str], out_file: str):
        with open(out_file, "w") as out:
            for path in in_files:
                with open(path) as f:
                    for line in f:
                        gen = self.generate_sample(line)
                        for sample in (gen() if callable(gen) else gen):
                            out.write(self._format(sample) + "\n")

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            gen = self.generate_sample(line)
            for sample in (gen() if callable(gen) else gen):
                sys.stdout.write(self._format(sample) + "\n")
