"""paddle.distributed (reference `python/paddle/distributed/`)."""
from . import collective, fleet, sharding, transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from .sharding import group_sharded_parallel, save_group_sharded_model
from .collective import (ReduceOp, all_gather, all_reduce, alltoall, barrier,
                         broadcast, get_group, new_group, recv, reduce,
                         reduce_scatter, scatter, send, shard_ctx, split,
                         wait)
from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env,
                  is_initialized)
from .parallel import DataParallel
from .tensor_parallel import (ColumnParallelLinear, RowParallelLinear,
                              VocabParallelEmbedding)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference `distributed/spawn.py:276`. TPU note: SPMD spans local
    chips from one process, so nprocs>1 is only for multi-host-style
    testing; it forks python processes wired with the PADDLE_* env."""
    import multiprocessing as mp
    import os
    if nprocs in (-1, 0, 1):
        func(*args)
        return
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs)}

        def target(rank=rank, env=env):
            os.environ.update(env)
            func(*args)
        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
