"""DistributeTranspiler — the legacy fluid PS program split (reference
`python/paddle/fluid/transpiler/distribute_transpiler.py:156`: rewrite a
single train Program into trainer programs that SEND gradients and
pserver programs that RECV + apply them).

TPU redesign: instead of splicing send/recv ops into a ProgramDesc, the
split is explicit over the op-list IR — `transpile` partitions parameters
round-robin across pserver endpoints as dense tables (the same TCP
service + native C++ table core the modern PS path uses), and the
trainer side wraps the lowered program: pull params → jax.grad on device
→ push grads; the optimizer rule runs table-side, exactly the reference's
sync-SGD dataflow."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """reference transpiler config (slice_var_up etc. — partitioning
    knobs). Only round-robin whole-param placement is implemented."""

    def __init__(self):
        self.slice_var_up = False
        self.split_method = "RoundRobin"
        self.min_block_size = 8192


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._placement: Dict[str, tuple] = {}   # name → (endpoint, tid)
        self._program = None
        self._trainers = 1
        self._pservers: List[str] = []

    def transpile(self, trainer_id, program=None, pservers="",
                  trainers=1, sync_mode=True, startup_program=None):
        from ..static.program import default_main_program
        self._program = program or default_main_program()
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._sync = sync_mode
        self._pservers = [e for e in pservers.split(",") if e]
        if not self._pservers:
            raise ValueError("transpile needs at least one pserver "
                             "endpoint")
        names = sorted(self._program.param_vars)
        for i, n in enumerate(names):
            ep = self._pservers[i % len(self._pservers)]
            self._placement[n] = (ep, i)
        return self

    # -- pserver side -------------------------------------------------------
    def get_pserver_program(self, endpoint):
        """Table configs this endpoint must host (the reference returns a
        recv+apply ProgramDesc; the rule-applying table IS that program
        here)."""
        from .ps.service import TableConfig
        opt = self._program._opt_hooks[-1] if self._program._opt_hooks \
            else None
        name = type(opt).__name__.lower() if opt else "sgd"
        supported = {"sgd": "sgd", "adam": "adam", "adamw": "adam"}
        if name not in supported:
            raise ValueError(
                f"pserver tables implement sgd/adam rules only; got "
                f"{type(opt).__name__} — use SGD/Adam(W) for the "
                f"transpiled PS mode (reference legacy PS had the same "
                f"per-rule server kernels)")
        rule = supported[name]
        if name == "adamw" and getattr(opt, "_weight_decay", 0.0):
            import warnings
            warnings.warn("AdamW weight decay is not applied by the "
                          "pserver adam rule; decoupled decay is dropped "
                          "in transpiled PS mode")
        from ..optimizer.lr import LRScheduler
        if opt is not None and isinstance(opt._lr, LRScheduler):
            import warnings
            warnings.warn("pserver tables apply a FIXED lr; the "
                          "LRScheduler will not take effect server-side")
        lr = opt.get_lr() if opt else 0.01
        cfgs = []
        for name, (ep, tid) in sorted(self._placement.items()):
            if ep != endpoint:
                continue
            v = self._program.param_vars[name]
            cfgs.append(TableConfig(tid, "dense",
                                    size=int(np.prod(v._value.shape)),
                                    rule=rule, lr=lr, name=name))
        return cfgs

    get_pserver_programs = get_pserver_program

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """Initial values each table must be seeded with (reference:
        the pserver startup program holding param init ops)."""
        from ..static.program import global_scope
        scope = global_scope()
        out = {}
        for name, (ep, tid) in self._placement.items():
            if endpoint is None or ep == endpoint:
                v = self._program.param_vars[name]
                init = scope.get(name, np.asarray(v._value))
                out[tid] = np.asarray(init, np.float32).reshape(-1)
        return out

    # -- trainer side -------------------------------------------------------
    def get_trainer_program(self, wait_port=True):
        """A runnable trainer: pull → device grad → push (the reference
        splices send/recv ops; here the wrapper is the program)."""
        return _TrainerProgram(self)


class _TrainerProgram:
    """Drives one trainer against the PS cluster. Callable like an
    Executor step: run(feed) → loss value."""

    def __init__(self, t: DistributeTranspiler):
        from .ps.service import PsClient
        self.t = t
        self.client = PsClient(t._pservers)
        self.program = t._program
        self._jit = None
        self._jit_key = None

    def _ensure_jit(self, fetch_slots):
        key = tuple(fetch_slots)
        if self._jit is not None and self._jit_key == key:
            return
        import jax

        from ..static.program import _Lowered
        program = self.program
        loss_slot = program._loss_slot
        self._lowered = _Lowered(program, [loss_slot] + list(fetch_slots))

        def loss_and_grads(feeds, pvals):
            def f(pv):
                return _Lowered(program, [loss_slot])(feeds, pv)[0]
            lv, g = jax.value_and_grad(f)(pvals)
            outs = _Lowered(program, list(fetch_slots))(feeds, pvals) \
                if fetch_slots else []
            return lv, g, outs
        self._jit = jax.jit(loss_and_grads)
        self._jit_key = key

    def run(self, feed=None, fetch_list=None):
        import jax.numpy as jnp

        fetch_slots = [v.slot for v in (fetch_list or [])]
        self._ensure_jit(fetch_slots)
        lowered, t = self._lowered, self.t
        feeds = []
        for n in lowered.feed_names:
            a = feed[n] if feed and n in feed else \
                self.program.feed_vars[n]._value
            feeds.append(jnp.asarray(np.asarray(
                a.numpy() if hasattr(a, "numpy") else a)))
        # pull current params from their tables
        pvals = []
        srv_of = {ep: i for i, ep in enumerate(t._pservers)}
        for n in lowered.param_names:
            ep, tid = t._placement[n]
            flat = self.client.pull_dense(tid, server=srv_of[ep])
            shape = self.program.param_vars[n]._value.shape
            pvals.append(jnp.asarray(flat.reshape(shape)))
        lv, grads, fetched = self._jit(feeds, pvals)
        # push grads scaled by 1/trainers (reference sync-SGD averages
        # across trainers); the table applies the rule per push
        scale = 1.0 / max(t._trainers, 1)
        for n, g in zip(lowered.param_names, grads):
            ep, tid = t._placement[n]
            self.client.push_dense(tid, (np.asarray(g, np.float32)
                                         * scale).reshape(-1),
                                   server=srv_of[ep])
        if t._sync:
            self.client.barrier()
        if fetch_list:
            return [float(np.asarray(lv))] + \
                [np.asarray(f) for f in fetched]
        return float(np.asarray(lv))

    def close(self):
        self.client.close()
