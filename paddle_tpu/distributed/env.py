"""Process-level distributed env (reference
`python/paddle/distributed/parallel.py:57` init_parallel_env +
`fleet/base/role_maker.py:528` PaddleCloudRoleMaker env parsing).

TPU model: one process per HOST (not per chip — SPMD covers local chips);
rendezvous = jax.distributed.initialize with a coordinator address. The
same PADDLE_* env vars the reference launcher sets are honored.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["ParallelEnv", "init_parallel_env", "get_rank", "get_world_size",
           "is_initialized"]

_initialized = False


class ParallelEnv:
    def __init__(self):
        self.rank = get_rank()
        self.world_size = get_world_size()
        self.device_id = 0
        self.current_endpoint = os.environ.get(
            "PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")
        self.trainer_endpoints = os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", self.current_endpoint).split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


def get_rank(group=None) -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size(group=None) -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def is_initialized() -> bool:
    return _initialized


def init_parallel_env(strategy=None) -> ParallelEnv:
    """Multi-host bootstrap. Single-host (this environment): builds the
    default all-devices mesh and returns. Multi-host: initializes the jax
    distributed runtime from PADDLE_* / JAX coordinator env vars, after
    which jax.devices() spans all hosts and meshes lay over ICI+DCN."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or \
        os.environ.get("PADDLE_MASTER") or None
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               os.environ.get("JAX_NUM_PROCESSES", "1")))
    pid = int(os.environ.get("PADDLE_TRAINER_ID",
                             os.environ.get("JAX_PROCESS_ID", "0")))
    if coord and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    # elastic mode: start this rank's heartbeat against the master's KV
    # server (reference ElasticManager; see fleet/elastic.py)
    kv_ep = os.environ.get("PADDLE_ELASTIC_KV")
    if kv_ep:
        from .fleet.elastic import HeartbeatClient
        HeartbeatClient(kv_ep, rank=pid).start()
    from ..parallel.mesh import create_mesh, get_mesh
    if get_mesh() is None:
        create_mesh({"dp": len(jax.devices())})
    _initialized = True
    return ParallelEnv()
