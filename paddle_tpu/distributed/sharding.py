"""paddle.distributed.sharding (reference
`python/paddle/distributed/sharding/group_sharded.py` group_sharded_parallel
— dygraph ZeRO stage 1/2/3).

TPU-native: returns the (model, optimizer, scaler) triple where the
optimizer is wrapped so that training through fleet / Model.fit builds an
SPMD step with ZeRO-sharded optimizer state (and, for stage 3, dp-sharded
parameters) — GSPMD inserts the gather/scatter collectives.
"""
from __future__ import annotations

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    """level: 'os' (ZeRO-1) | 'os_g' (ZeRO-2) | 'p_g_os' (ZeRO-3)."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level, 1)
    from ..parallel.mesh import get_mesh
    from ..parallel.spmd import shard_params
    from jax.sharding import PartitionSpec

    if stage >= 3 and get_mesh() is not None:
        # dp-shard the parameters themselves on their largest divisible axis
        mesh = get_mesh()
        dp = mesh.shape.get("dp", 1)
        if dp > 1:
            for _, p in model.named_parameters():
                if getattr(p, "partition_spec", None):
                    continue
                shape = tuple(p._value.shape)
                for ax, d in sorted(enumerate(shape),
                                    key=lambda t: -t[1]):
                    if d % dp == 0:
                        spec = [None] * len(shape)
                        spec[ax] = "dp"
                        p.partition_spec = PartitionSpec(*spec)
                        break
        shard_params(model)
    optimizer._zero_stage = stage
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ..framework.io_state import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
