from . import datasets, models, ops, transforms
from .models import *  # noqa: F401,F403
