from . import datasets, models, transforms
from .models import *  # noqa: F401,F403
