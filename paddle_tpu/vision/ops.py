"""Vision ops (reference `python/paddle/vision/ops.py` + detection ops in
`paddle/fluid/operators/detection/`): nms, roi_align, yolo_box, box_coder,
deform_conv2d (API parity subset for the detection model families)."""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op

__all__ = ["nms", "roi_align", "roi_pool", "yolo_box", "yolov3_loss",
           "anchor_generator", "prior_box", "generate_proposals",
           "multiclass_nms", "box_coder",
           "box_iou", "distribute_fpn_proposals"]


def box_iou(boxes1, boxes2):
    def impl(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0, None)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)
    return apply_op("box_iou", impl, (boxes1, boxes2), {})


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, offset=0.0, eta=1.0):
    """Greedy NMS (reference `operators/detection/nms_op` /
    multiclass_nms). Dynamic output ⇒ eager (numpy) like the reference's
    CPU path; scoring models run the box head on TPU, NMS on host.
    offset: 1.0 for the un-normalized pixel convention (w = x2-x1+1);
    eta < 1 decays the threshold after each kept box while it exceeds
    0.5 (the reference's adaptive NMS)."""
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    s = (np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores)
         if scores is not None else np.arange(len(b))[::-1].astype("float32"))
    cat = (np.asarray(category_idxs.numpy()
                      if isinstance(category_idxs, Tensor) else category_idxs)
           if category_idxs is not None else np.zeros(len(b), np.int64))

    keep_all = []
    for c in np.unique(cat):
        idx = np.where(cat == c)[0]
        order = idx[np.argsort(-s[idx])]
        keep = []
        thr = float(iou_threshold)
        while order.size:
            i = order[0]
            keep.append(i)
            if order.size == 1:
                break
            rest = order[1:]
            xx1 = np.maximum(b[i, 0], b[rest, 0])
            yy1 = np.maximum(b[i, 1], b[rest, 1])
            xx2 = np.minimum(b[i, 2], b[rest, 2])
            yy2 = np.minimum(b[i, 3], b[rest, 3])
            w = np.clip(xx2 - xx1 + offset, 0, None)
            h = np.clip(yy2 - yy1 + offset, 0, None)
            inter = w * h
            a1 = (b[i, 2] - b[i, 0] + offset) * \
                (b[i, 3] - b[i, 1] + offset)
            a2 = (b[rest, 2] - b[rest, 0] + offset) * \
                (b[rest, 3] - b[rest, 1] + offset)
            iou = inter / (a1 + a2 - inter + 1e-10)
            order = rest[iou <= thr]
            if eta < 1.0 and thr > 0.5:
                thr *= eta
        keep_all.extend(keep)
    keep_all = sorted(keep_all, key=lambda i: -s[i])
    if top_k is not None:
        keep_all = keep_all[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep_all, np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear sampling (reference
    `operators/roi_align_op`), static-shape and jittable."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def impl(feat, rois, rois_num):
        # feat [N,C,H,W]; rois [R,4] in input coords; rois_num [N]
        N, C, H, W = feat.shape
        R = rois.shape[0]
        batch_idx = jnp.repeat(jnp.arange(N), rois_num, axis=0,
                               total_repeat_length=R)
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        bw = jnp.maximum(x2 - x1, 1e-6)
        bh = jnp.maximum(y2 - y1, 1e-6)
        # sample grid: [R, oh*sr, ow*sr]
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :]
              * bh[:, None] / (oh * sr))
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :]
              * bw[:, None] / (ow * sr))

        def bilinear(r):
            f = feat[batch_idx[r]]  # [C,H,W]
            yy = jnp.clip(ys[r], 0, H - 1)
            xx = jnp.clip(xs[r], 0, W - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, H - 1)
            x1_ = jnp.minimum(x0 + 1, W - 1)
            wy = yy - y0
            wx = xx - x0
            # gather [C, oh*sr, ow*sr]
            def gat(yi, xi):
                return f[:, yi][:, :, xi]
            v = (gat(y0, x0) * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                 + gat(y1_, x0) * wy[None, :, None] * (1 - wx)[None, None, :]
                 + gat(y0, x1_) * (1 - wy)[None, :, None] * wx[None, None, :]
                 + gat(y1_, x1_) * wy[None, :, None] * wx[None, None, :])
            v = v.reshape(C, oh, sr, ow, sr).mean(axis=(2, 4))
            return v
        return jax.vmap(bilinear)(jnp.arange(R))
    return apply_op("roi_align", impl, (x, boxes, boxes_num), {})


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """reference `operators/roi_pool_op.cc` — true quantized-bin max pool
    (roi_align's bilinear sampling is the smooth variant)."""
    from ..ops.extra_ops import roi_pool as _impl
    return _impl(x, boxes, boxes_num, output_size, spatial_scale)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0, name=None):
    """reference `operators/detection/yolo_box_op`."""
    na = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(na, 2)

    def impl(feat, imgs):
        N, C, H, W = feat.shape
        feat = feat.reshape(N, na, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)
        gy = jnp.arange(H, dtype=jnp.float32)
        sx = jax.nn.sigmoid(feat[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        sy = jax.nn.sigmoid(feat[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        bx = (gx[None, None, None, :] + sx) / W
        by = (gy[None, None, :, None] + sy) / H
        bw = jnp.exp(feat[:, :, 2]) * anc[None, :, 0, None, None] / \
            (W * downsample_ratio)
        bh = jnp.exp(feat[:, :, 3]) * anc[None, :, 1, None, None] / \
            (H * downsample_ratio)
        conf = jax.nn.sigmoid(feat[:, :, 4])
        probs = jax.nn.sigmoid(feat[:, :, 5:]) * conf[:, :, None]
        imw = imgs[:, 1].astype(jnp.float32)
        imh = imgs[:, 0].astype(jnp.float32)
        x1 = (bx - bw / 2) * imw[:, None, None, None]
        y1 = (by - bh / 2) * imh[:, None, None, None]
        x2 = (bx + bw / 2) * imw[:, None, None, None]
        y2 = (by + bh / 2) * imh[:, None, None, None]
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw[:, None, None, None] - 1)
            y1 = jnp.clip(y1, 0, imh[:, None, None, None] - 1)
            x2 = jnp.clip(x2, 0, imw[:, None, None, None] - 1)
            y2 = jnp.clip(y2, 0, imh[:, None, None, None] - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
        mask = scores.max(-1) >= conf_thresh
        scores = jnp.where(mask[..., None], scores, 0.0)
        return boxes, scores
    return apply_op("yolo_box", impl, (x, img_size), {})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """reference `operators/detection/box_coder_op` (decode path)."""
    def impl(prior, var, tgt):
        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = prior[:, 0] + 0.5 * pw
        pcy = prior[:, 1] + 0.5 * ph
        if code_type == "decode_center_size":
            tx, ty, tw, th = (tgt[..., 0], tgt[..., 1], tgt[..., 2],
                              tgt[..., 3])
            cx = var[..., 0] * tx * pw + pcx
            cy = var[..., 1] * ty * ph + pcy
            w = jnp.exp(var[..., 2] * tw) * pw
            h = jnp.exp(var[..., 3] * th) * ph
            return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                              cy + h / 2], axis=-1)
        tw_ = tgt[:, 2] - tgt[:, 0]
        th_ = tgt[:, 3] - tgt[:, 1]
        tcx = tgt[:, 0] + 0.5 * tw_
        tcy = tgt[:, 1] + 0.5 * th_
        return jnp.stack([(tcx - pcx) / pw / var[..., 0],
                          (tcy - pcy) / ph / var[..., 1],
                          jnp.log(tw_ / pw) / var[..., 2],
                          jnp.log(th_ / ph) / var[..., 3]], axis=-1)
    return apply_op("box_coder", impl,
                    (prior_box, prior_box_var, target_box), {})


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """reference `operators/detection/distribute_fpn_proposals_op` —
    eager (dynamic outputs)."""
    rois = np.asarray(fpn_rois.numpy() if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.clip(w * h, 1e-6, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs, idxs = [], []
    for l in range(min_level, max_level + 1):
        sel = np.where(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    restore = np.argsort(np.concatenate(idxs)) if idxs else np.array([])
    return outs, Tensor(jnp.asarray(restore.astype(np.int32)))


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """reference `operators/detection/yolov3_loss_op.cc`.

    x: [N, mask_num*(5+class_num), H, W] raw head output; gt_box
    [N, B, 4] (cx, cy, w, h normalized to the image); gt_label [N, B];
    anchors: flat [w0,h0,w1,h1,...] in input pixels; anchor_mask: indices
    of this scale's anchors. Per-sample scalar loss [N]: BCE on x/y
    offsets and objectness/class logits, L1 on w/h, box-size weighting
    (2 - w*h), noobj predictions with best-gt IoU > ignore_thresh
    excluded. Decode conventions match yolo_box above.
    """
    all_anc = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    na = len(mask)
    manc = all_anc[mask]                       # [na, 2]

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def impl(feat, gbox, glabel, gscore=None):
        N, C, H, W = feat.shape
        feat = feat.reshape(N, na, 5 + class_num, H, W)
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        B = gbox.shape[1]
        valid = (gbox[:, :, 2] > 0) & (gbox[:, :, 3] > 0)   # [N,B]

        # --- gt -> (anchor, cell) assignment: best w/h IoU over ALL
        # anchors (centered boxes), kept only if that anchor is masked
        gw = gbox[:, :, 2] * in_w
        gh = gbox[:, :, 3] * in_h
        inter = jnp.minimum(gw[..., None], all_anc[None, None, :, 0]) * \
            jnp.minimum(gh[..., None], all_anc[None, None, :, 1])
        union = gw[..., None] * gh[..., None] + \
            (all_anc[:, 0] * all_anc[:, 1])[None, None] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)  # [N,B]
        mask_arr = jnp.asarray(mask)
        an_idx = jnp.argmax(best[..., None] == mask_arr[None, None], -1)
        assigned = valid & (best[..., None] == mask_arr[None, None]
                            ).any(-1)                            # [N,B]

        gi = jnp.clip((gbox[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gbox[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
        tx = gbox[:, :, 0] * W - gi
        ty = gbox[:, :, 1] * H - gj
        tw = jnp.log(jnp.maximum(gw, 1e-9) /
                     jnp.maximum(manc[an_idx][..., 0], 1e-9))
        th = jnp.log(jnp.maximum(gh, 1e-9) /
                     jnp.maximum(manc[an_idx][..., 1], 1e-9))
        box_w = 2.0 - gbox[:, :, 2] * gbox[:, :, 3]        # size weight

        n_ix = jnp.arange(N)[:, None].repeat(B, 1)
        sel = (n_ix, an_idx, gi, gj)                       # gather coords
        px = feat[:, :, 0].transpose(0, 1, 3, 2)[sel]      # logit tx
        py = feat[:, :, 1].transpose(0, 1, 3, 2)[sel]
        pw = feat[:, :, 2].transpose(0, 1, 3, 2)[sel]
        ph = feat[:, :, 3].transpose(0, 1, 3, 2)[sel]
        pobj = feat[:, :, 4].transpose(0, 1, 3, 2)[sel]
        pcls = feat[:, :, 5:].transpose(0, 1, 4, 3, 2)[sel]  # [N,B,cls]

        w = (assigned * box_w)
        sc = gscore if gscore is not None else jnp.ones_like(w)
        loss_xy = (bce(px, tx) + bce(py, ty)) * w * sc
        loss_wh = (jnp.abs(pw - tw) + jnp.abs(ph - th)) * w * sc
        loss_obj_pos = bce(pobj, jnp.ones_like(pobj)) * assigned * sc

        # reference: smooth_weight = min(1/class_num, 1/40); pos 1-s, neg s
        smooth = min(1.0 / max(class_num, 1), 1.0 / 40) \
            if use_label_smooth else 0.0
        onehot = (glabel[..., None] == jnp.arange(class_num)).astype(
            jnp.float32)
        onehot = onehot * (1 - 2 * smooth) + smooth
        loss_cls = (bce(pcls, onehot).sum(-1) * assigned * sc)

        # --- noobj objectness: all predictions except assigned ones,
        # with best-gt-IoU > ignore_thresh excluded
        gx0 = jnp.arange(W, dtype=jnp.float32)
        gy0 = jnp.arange(H, dtype=jnp.float32)
        bx = (gx0[None, None, None] + jax.nn.sigmoid(feat[:, :, 0])) / W
        by = (gy0[None, None, :, None] + jax.nn.sigmoid(feat[:, :, 1])) / H
        bw = jnp.exp(jnp.clip(feat[:, :, 2], -10, 10)) * \
            manc[None, :, 0, None, None] / in_w
        bh = jnp.exp(jnp.clip(feat[:, :, 3], -10, 10)) * \
            manc[None, :, 1, None, None] / in_h

        def iou_with_gts(bx, by, bw, bh, gb, gvalid):
            px1, px2 = bx - bw / 2, bx + bw / 2
            py1, py2 = by - bh / 2, by + bh / 2
            g = gb[:, :, None, None, None]        # [N,B,1,1,1,(4)]
            gx1 = g[..., 0] - g[..., 2] / 2
            gx2 = g[..., 0] + g[..., 2] / 2
            gy1 = g[..., 1] - g[..., 3] / 2
            gy2 = g[..., 1] + g[..., 3] / 2
            iw = jnp.maximum(
                jnp.minimum(px2[:, None], gx2) -
                jnp.maximum(px1[:, None], gx1), 0)
            ih = jnp.maximum(
                jnp.minimum(py2[:, None], gy2) -
                jnp.maximum(py1[:, None], gy1), 0)
            inter = iw * ih
            ua = bw[:, None] * bh[:, None] + g[..., 2] * g[..., 3] - inter
            iou = inter / jnp.maximum(ua, 1e-9)
            return jnp.where(gvalid[:, :, None, None, None], iou,
                             0.0).max(1)
        best_iou = iou_with_gts(bx, by, bw, bh, gbox, valid)  # [N,na,H,W]

        # .max == logical OR: padded gts share index (n,0,0,0) with real
        # assignments and a scatter-set could clobber True with False
        is_assigned = jnp.zeros((N, na, W, H), bool).at[sel].max(
            assigned, mode="drop").transpose(0, 1, 3, 2)      # [N,na,H,W]
        noobj = (~is_assigned) & (best_iou <= ignore_thresh)
        loss_noobj = (bce(feat[:, :, 4], jnp.zeros_like(feat[:, :, 4]))
                      * noobj).sum((1, 2, 3))

        per_gt = (loss_xy + loss_wh + loss_obj_pos + loss_cls)
        return per_gt.sum(1) + loss_noobj

    if gt_score is not None:
        return apply_op("yolov3_loss", impl,
                        (x, gt_box, gt_label, gt_score), {})
    return apply_op("yolov3_loss",
                    functools.partial(impl, gscore=None),
                    (x, gt_box, gt_label), {})


def anchor_generator(input, anchor_sizes, aspect_ratios, variance=None,
                     stride=None, offset=0.5, name=None):
    """reference `operators/detection/anchor_generator_op.cc` (RPN
    anchors): per feature-map cell, one anchor per (size, ratio) pair,
    centered with `offset`, in input-image coordinates.
    Returns (anchors [H, W, A, 4] xyxy, variances [H, W, A, 4])."""
    H, W = int(input.shape[2]), int(input.shape[3])
    stride = stride or [16.0, 16.0]
    variance = variance or [0.1, 0.1, 0.2, 0.2]
    combos = [(s, r) for r in aspect_ratios for s in anchor_sizes]
    A = len(combos)
    anc = np.zeros((H, W, A, 4), np.float32)
    cx = (np.arange(W) + offset) * stride[0]
    cy = (np.arange(H) + offset) * stride[1]
    for a, (s, r) in enumerate(combos):
        # reference convention: aspect_ratio = h/w (anchor_generator_op)
        aw = s / float(np.sqrt(r))
        ah = s * float(np.sqrt(r))
        anc[:, :, a, 0] = cx[None, :] - aw / 2
        anc[:, :, a, 1] = cy[:, None] - ah / 2
        anc[:, :, a, 2] = cx[None, :] + aw / 2
        anc[:, :, a, 3] = cy[:, None] + ah / 2
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          (H, W, A, 4)).copy()
    return Tensor(jnp.asarray(anc)), Tensor(jnp.asarray(var))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=True, clip=False, steps=None,
              offset=0.5, name=None):
    """reference `operators/detection/prior_box_op.cc` (SSD priors):
    normalized [0,1] boxes per cell from min/max sizes and ratios.
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    H, W = int(input.shape[2]), int(input.shape[3])
    imH, imW = int(image.shape[2]), int(image.shape[3])
    aspect_ratios = list(aspect_ratios or [1.0])
    ratios = [1.0]
    for r in aspect_ratios:
        if all(abs(r - e) > 1e-6 for e in ratios):
            ratios.append(r)
            if flip:
                ratios.append(1.0 / r)
    variance = variance or [0.1, 0.1, 0.2, 0.2]
    # reference sentinel: step 0 means "derive from image/feature ratio"
    if not steps or steps[0] == 0 or steps[1] == 0:
        steps = [imW / W, imH / H]
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        for r in ratios:
            boxes.append((ms * np.sqrt(r), ms / np.sqrt(r)))
        if max_sizes:
            big = np.sqrt(ms * max_sizes[ms_i])
            boxes.append((big, big))
    P = len(boxes)
    out = np.zeros((H, W, P, 4), np.float32)
    cx = (np.arange(W) + offset) * steps[0] / imW
    cy = (np.arange(H) + offset) * steps[1] / imH
    for p, (bw, bh) in enumerate(boxes):
        out[:, :, p, 0] = cx[None, :] - bw / imW / 2
        out[:, :, p, 1] = cy[:, None] - bh / imH / 2
        out[:, :, p, 2] = cx[None, :] + bw / imW / 2
        out[:, :, p, 3] = cy[:, None] + bh / imH / 2
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          (H, W, P, 4)).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=True, name=None):
    """reference `operators/detection/generate_proposals_op.cc` (RPN):
    decode deltas on anchors, clip to image, drop tiny boxes, NMS, keep
    post_nms_top_n. Dynamic output ⇒ eager host math like nms() above.
    scores [N, A, H, W]; bbox_deltas [N, 4*A, H, W]; anchors/variances
    [H, W, A, 4]. Returns (rois [R,4], roi_scores [R,1], rois_num [N])."""
    sc = np.asarray(scores.numpy() if isinstance(scores, Tensor)
                    else scores)
    bd = np.asarray(bbox_deltas.numpy()
                    if isinstance(bbox_deltas, Tensor) else bbox_deltas)
    anc = np.asarray(anchors.numpy() if isinstance(anchors, Tensor)
                     else anchors).reshape(-1, 4)
    var = np.asarray(variances.numpy() if isinstance(variances, Tensor)
                     else variances).reshape(-1, 4)
    im = np.asarray(img_size.numpy() if isinstance(img_size, Tensor)
                    else img_size)
    N, A, H, W = sc.shape
    all_rois, all_scores, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)           # H*W*A
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1
                                                ).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], anc[order], var[order]
        aw = a[:, 2] - a[:, 0]
        ah = a[:, 3] - a[:, 1]
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        imh, imw = float(im[n, 0]), float(im[n, 1])
        x1 = np.clip(cx - w / 2, 0, imw - 1)
        y1 = np.clip(cy - h / 2, 0, imh - 1)
        x2 = np.clip(cx + w / 2, 0, imw - 1)
        y2 = np.clip(cy + h / 2, 0, imh - 1)
        keep = ((x2 - x1 + 1) >= min_size) & ((y2 - y1 + 1) >= min_size)
        boxes = np.stack([x1, y1, x2, y2], 1)[keep]
        s = s[keep]
        kept = nms(boxes, iou_threshold=nms_thresh, scores=s,
                   top_k=post_nms_top_n, eta=eta, offset=1.0)
        ki = np.asarray(kept.numpy(), int)
        all_rois.append(boxes[ki])
        all_scores.append(s[ki, None])
        nums.append(len(ki))
    rois = np.concatenate(all_rois, 0) if all_rois else \
        np.zeros((0, 4), np.float32)
    rs = np.concatenate(all_scores, 0) if all_scores else \
        np.zeros((0, 1), np.float32)
    out = (Tensor(jnp.asarray(rois.astype(np.float32))),
           Tensor(jnp.asarray(rs.astype(np.float32))))
    if return_rois_num:
        return out + (Tensor(jnp.asarray(np.asarray(nums, np.int32))),)
    return out


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=1000,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """reference `operators/detection/multiclass_nms_op.cc`: per-class
    NMS (one nms() call with category_idxs) then global keep_top_k.
    bboxes [N, M, 4]; scores [N, C, M]; class `background_label` is
    skipped (reference default 0). Returns (out [R, 6] =
    (label, score, x1, y1, x2, y2), rois_num [N])."""
    b = np.asarray(bboxes.numpy() if isinstance(bboxes, Tensor)
                   else bboxes)
    s = np.asarray(scores.numpy() if isinstance(scores, Tensor)
                   else scores)
    N, C, M = s.shape
    outs, nums = [], []
    for n in range(N):
        cand_b, cand_s, cand_c = [], [], []
        for c in range(C):
            if c == background_label:
                continue
            m = s[n, c] > score_threshold
            if not m.any():
                continue
            cb, cs = b[n][m], s[n, c][m]
            order = np.argsort(-cs)[:nms_top_k]
            cand_b.append(cb[order])
            cand_s.append(cs[order])
            cand_c.append(np.full(len(order), c, np.int64))
        if not cand_b:
            nums.append(0)
            continue
        cb = np.concatenate(cand_b, 0)
        cs = np.concatenate(cand_s, 0)
        cc = np.concatenate(cand_c, 0)
        kept = np.asarray(nms(cb, iou_threshold=nms_threshold, scores=cs,
                              category_idxs=cc, top_k=keep_top_k,
                              eta=nms_eta,
                              offset=0.0 if normalized else 1.0
                              ).numpy(), int)
        outs.extend((cc[k], cs[k], *cb[k]) for k in kept)
        nums.append(len(kept))
    arr = np.asarray(outs, np.float32) if outs else \
        np.zeros((0, 6), np.float32)
    return (Tensor(jnp.asarray(arr)),
            Tensor(jnp.asarray(np.asarray(nums, np.int32))))
