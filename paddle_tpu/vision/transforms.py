"""Vision transforms (reference `python/paddle/vision/transforms/`):
numpy/CHW-HWC based, composable."""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "to_tensor", "normalize", "resize", "hflip", "vflip"]


def _hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 → CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def to_tensor(img, data_format="CHW"):
    img = _hwc(img).astype("float32")
    if img.max() > 1.5:
        img = img / 255.0
    if data_format == "CHW":
        img = img.transpose(2, 0, 1)
    return img


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype="float32")
        self.std = np.asarray(std, dtype="float32")
        self.data_format = data_format

    def __call__(self, img):
        return normalize(np.asarray(img, dtype="float32"), self.mean,
                         self.std, self.data_format)


def normalize(img, mean, std, data_format="CHW"):
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (img - mean.reshape(shape)) / std.reshape(shape)


def _resize_np(img, size):
    """nearest-neighbor resize without external deps."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    ys = (np.arange(nh) * (h / nh)).astype(int).clip(0, h - 1)
    xs = (np.arange(nw) * (w / nw)).astype(int).clip(0, w - 1)
    return img[ys][:, xs]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return resize(_hwc(img), self.size)


def resize(img, size, interpolation="bilinear"):
    return _resize_np(_hwc(img), size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        img = _hwc(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, ((p, p), (p, p), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return img[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = _hwc(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        img = _hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return _resize_np(img[i:i + th, j:j + tw], self.size)
        return _resize_np(img, self.size)


def hflip(img):
    return _hwc(img)[:, ::-1]


def vflip(img):
    return _hwc(img)[::-1]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return _hwc(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return _hwc(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return _hwc(img).transpose(self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(_hwc(img) * alpha, 0, 255).astype(_hwc(img).dtype)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding

    def __call__(self, img):
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        img = _hwc(img)
        return np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
