"""YOLOv3 detection model (reference: PaddleDetection's YOLOv3 built on
`operators/detection/{yolov3_loss,yolo_box,multiclass_nms}`; the base
framework ships the ops — this model family wires them the way the
reference ecosystem does).

TPU-first: a compact DarkNet-style backbone of strided convs (static
shapes, bf16-friendly), an upsample+concat neck, and one detection head
per scale. `forward` returns raw head outputs; `loss` sums yolov3_loss
over scales; `predict` decodes via yolo_box and merges scales.
"""
from __future__ import annotations


from ... import nn
from ...nn import functional as F
from ...ops.manipulation import concat
from ..ops import yolo_box, yolov3_loss

__all__ = ["YOLOv3", "yolov3"]

# anchors per scale (COCO defaults), flat [w, h] pairs
_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
            116, 90, 156, 198, 373, 326]
# large anchors pair with the coarse stride-32 head (reference
# PaddleDetection convention for downsamples 32/16/8)
_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


def _conv_bn(cin, cout, k, stride=1):
    return nn.Sequential(
        nn.Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                  bias_attr=False),
        nn.BatchNorm2D(cout),
        nn.LeakyReLU(0.1))


class _Stage(nn.Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.down = _conv_bn(cin, cout, 3, stride=2)
        self.conv1 = _conv_bn(cout, cout // 2, 1)
        self.conv2 = _conv_bn(cout // 2, cout, 3)

    def forward(self, x):
        x = self.down(x)
        return x + self.conv2(self.conv1(x))


class YOLOv3(nn.Layer):
    """Three-scale YOLOv3. `width` scales the channel counts (the full
    DarkNet53 uses width=64; the default keeps the model compile-fast
    while structurally identical)."""

    def __init__(self, num_classes=80, width=16):
        super().__init__()
        self.num_classes = num_classes
        w = width
        self.stem = _conv_bn(3, w, 3)
        self.c2 = _Stage(w, w * 2)        # /2
        self.c3 = _Stage(w * 2, w * 4)    # /4
        self.c4 = _Stage(w * 4, w * 8)    # /8   -> P3 source
        self.c5 = _Stage(w * 8, w * 16)   # /16  -> P4 source
        self.c6 = _Stage(w * 16, w * 32)  # /32  -> P5 source

        co = 3 * (5 + num_classes)
        self.head5 = nn.Conv2D(w * 32, co, 1)
        self.lat5 = _conv_bn(w * 32, w * 8, 1)
        self.head4 = nn.Conv2D(w * 16 + w * 8, co, 1)
        self.lat4 = _conv_bn(w * 16 + w * 8, w * 4, 1)
        self.head3 = nn.Conv2D(w * 8 + w * 4, co, 1)

    def forward(self, x):
        x = self.stem(x)
        x = self.c3(self.c2(x))
        p3 = self.c4(x)
        p4 = self.c5(p3)
        p5 = self.c6(p4)

        out5 = self.head5(p5)
        up5 = F.interpolate(self.lat5(p5), scale_factor=2, mode="nearest")
        m4 = concat([p4, up5], axis=1)
        out4 = self.head4(m4)
        up4 = F.interpolate(self.lat4(m4), scale_factor=2, mode="nearest")
        m3 = concat([p3, up4], axis=1)
        out3 = self.head3(m3)
        # large->small stride order matches the anchor masks
        return [out5, out4, out3]

    def loss(self, outputs, gt_box, gt_label, gt_score=None,
             ignore_thresh=0.7):
        total = None
        for out, mask, ds in zip(outputs, _MASKS, (32, 16, 8)):
            l = yolov3_loss(out, gt_box, gt_label, _ANCHORS, mask,
                            self.num_classes, ignore_thresh,
                            downsample_ratio=ds, gt_score=gt_score)
            total = l if total is None else total + l
        return total

    def predict(self, outputs, img_size, conf_thresh=0.01):
        boxes, scores = [], []
        for out, mask, ds in zip(outputs, _MASKS, (32, 16, 8)):
            anc = []
            for m in mask:
                anc += _ANCHORS[2 * m:2 * m + 2]
            b, s = yolo_box(out, img_size, anc, self.num_classes,
                            conf_thresh=conf_thresh, downsample_ratio=ds)
            boxes.append(b)
            scores.append(s)
        return concat(boxes, axis=1), concat(scores, axis=1)


def yolov3(pretrained=False, num_classes=80, **kwargs):
    if pretrained:
        import warnings
        warnings.warn("pretrained YOLOv3 weights unavailable offline; "
                      "returning a randomly initialized model")
    return YOLOv3(num_classes=num_classes, **kwargs)
