"""MobileNetV1/V2 (reference `python/paddle/vision/models/mobilenetv1.py`,
`mobilenetv2.py`)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = nn.ReLU6() if act == "relu6" else nn.ReLU() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, stride, scale):
        super().__init__()
        c1 = int(out_c1 * scale)
        c2 = int(out_c2 * scale)
        self.dw = ConvBNLayer(in_c, c1, 3, stride, 1, groups=in_c)
        self.pw = ConvBNLayer(c1, c2, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        self.conv1 = ConvBNLayer(3, s(32), 3, 2, 1)
        cfg = [(s(32), 32, 64, 1), (s(64), 64, 128, 2),
               (s(128), 128, 128, 1), (s(128), 128, 256, 2),
               (s(256), 256, 256, 1), (s(256), 256, 512, 2)] + \
              [(s(512), 512, 512, 1)] * 5 + \
              [(s(512), 512, 1024, 2), (s(1024), 1024, 1024, 1)]
        blocks = [DepthwiseSeparable(ic, c1, c2, st, scale)
                  for ic, c1, c2, st in cfg]
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.blocks(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride, 1, groups=hidden,
                        act="relu6"),
            ConvBNLayer(hidden, oup, 1, act=None)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = int(32 * scale)
        last_c = int(1280 * max(1.0, scale))
        feats = [ConvBNLayer(3, in_c, 3, 2, 1, act="relu6")]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(in_c, out_c,
                                              s if i == 0 else 1, t))
                in_c = out_c
        feats.append(ConvBNLayer(in_c, last_c, 1, act="relu6"))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
