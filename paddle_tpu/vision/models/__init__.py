from .lenet import LeNet
from .mobilenet import (MobileNetV1, MobileNetV2, mobilenet_v1,
                        mobilenet_v2)
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .yolov3 import YOLOv3, yolov3
