"""Vision datasets (reference `python/paddle/vision/datasets/`).

This environment has zero egress, so `download=True` cannot fetch; datasets
read local files when present (same on-disk formats as the reference) and
otherwise fall back to a deterministic synthetic sample set (`mode` data
keeps shape/dtype contracts so pipelines exercise identically).
"""
from __future__ import annotations

import gzip
import os
import struct
import warnings

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers"]


def _synthetic(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    images = (rng.rand(n, *shape) * 255).astype("uint8")
    labels = rng.randint(0, num_classes, size=(n,)).astype("int64")
    return images, labels


class MNIST(Dataset):
    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        images = labels = None
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                labels = np.frombuffer(f.read(), dtype=np.uint8).astype(
                    "int64")
        if images is None:
            warnings.warn(f"{type(self).__name__}: no local data; using "
                          "deterministic synthetic samples (offline env)")
            n = 1024 if mode == "train" else 256
            images, labels = _synthetic(
                n, (28, 28), self.NUM_CLASSES,
                seed=42 if mode == "train" else 43)
        self.images = images
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[None, :, :] / 255.0
        if self.transform is not None:
            img = self.transform(self.images[idx])
        return img, np.asarray(self.labels[idx], dtype="int64")

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            import tarfile
            datas, labels = [], []
            with tarfile.open(data_file) as tf:
                names = [n for n in tf.getnames()
                         if ("data_batch" in n if mode == "train"
                             else "test_batch" in n)]
                for name in sorted(names):
                    d = pickle.load(tf.extractfile(name), encoding="bytes")
                    datas.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
            self.images = np.concatenate(datas).reshape(-1, 3, 32, 32) \
                .transpose(0, 2, 3, 1)
            self.labels = np.asarray(labels, dtype="int64")
        else:
            warnings.warn(f"{type(self).__name__}: no local data; using "
                          "deterministic synthetic samples (offline env)")
            n = 1024 if mode == "train" else 256
            self.images, self.labels = _synthetic(
                n, (32, 32, 3), self.NUM_CLASSES,
                seed=44 if mode == "train" else 45)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype("float32").transpose(2, 0, 1) / 255.0
        return img, np.asarray(self.labels[idx], dtype="int64")

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Dataset):
    NUM_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        warnings.warn("Flowers: synthetic fallback (offline env)")
        n = 512 if mode == "train" else 128
        self.images, self.labels = _synthetic(n, (64, 64, 3),
                                              self.NUM_CLASSES, seed=46)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype("float32").transpose(2, 0, 1) / 255.0
        return img, np.asarray(self.labels[idx], dtype="int64")

    def __len__(self):
        return len(self.images)
