"""Reference .pdmodel / .pdiparams wire-format reader.

Schema facts (field numbers, enum values, stream framing) come from:
  - `paddle/fluid/framework/framework.proto:43-207` (ProgramDesc ⊃
    BlockDesc ⊃ OpDesc/VarDesc, AttrType, VarType.Type)
  - `paddle/fluid/framework/lod_tensor.cc:244` SerializeToStream
    (u32 version, u64 lod_level, per-level u64 size + data)
  - `paddle/fluid/framework/tensor_util.cc` TensorToStream
    (u32 version, i32 TensorDesc size, TensorDesc proto, raw data)
  - `paddle/fluid/operators/save_combine_op.h:34` (tensors concatenated
    in input-name order)

The decoder is a generic protobuf-2 wire parser (varint / 64-bit /
length-delimited / 32-bit), schema-driven — no generated code, no .proto
file — so the same ~100 lines also parse TensorDesc and future messages.
"""
from __future__ import annotations

import io
import struct
from typing import Any, Dict, List, Tuple

import numpy as np

__all__ = ["parse_program_desc", "read_combined_params",
           "read_lod_tensor_stream"]


# ---------------------------------------------------------------------------
# generic proto2 wire decoding
# ---------------------------------------------------------------------------

def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _skip(buf, pos, wire):
    if wire == 0:
        _, pos = _read_varint(buf, pos)
    elif wire == 1:
        pos += 8
    elif wire == 2:
        n, pos = _read_varint(buf, pos)
        pos += n
    elif wire == 5:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire}")
    return pos


def _decode(buf: memoryview, schema: Dict[int, tuple]) -> Dict[str, Any]:
    """schema: field_no → (name, kind[, sub_schema]); kind ∈ varint,
    float, double, string, bytes, message, and repeated_* variants.
    Repeated scalar fields accept both packed and unpacked encodings."""
    out: Dict[str, Any] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        spec = schema.get(field)
        if spec is None:
            pos = _skip(buf, pos, wire)
            continue
        name, kind = spec[0], spec[1]
        repeated = kind.startswith("repeated_")
        base = kind[len("repeated_"):] if repeated else kind

        def put(v):
            if repeated:
                out.setdefault(name, []).append(v)
            else:
                out[name] = v

        if base == "varint":
            if wire == 2:             # packed repeated varints
                n, pos = _read_varint(buf, pos)
                end = pos + n
                while pos < end:
                    v, pos = _read_varint(buf, pos)
                    put(v)
            else:
                v, pos = _read_varint(buf, pos)
                put(v)
        elif base == "float":
            if wire == 2:
                n, pos = _read_varint(buf, pos)
                for i in range(n // 4):
                    put(struct.unpack_from("<f", buf, pos + 4 * i)[0])
                pos += n
            else:
                put(struct.unpack_from("<f", buf, pos)[0])
                pos += 4
        elif base == "double":
            if wire == 2:
                n, pos = _read_varint(buf, pos)
                for i in range(n // 8):
                    put(struct.unpack_from("<d", buf, pos + 8 * i)[0])
                pos += n
            else:
                put(struct.unpack_from("<d", buf, pos)[0])
                pos += 8
        elif base in ("string", "bytes", "message"):
            n, pos = _read_varint(buf, pos)
            chunk = buf[pos:pos + n]
            pos += n
            if base == "string":
                put(bytes(chunk).decode("utf-8"))
            elif base == "bytes":
                put(bytes(chunk))
            else:
                put(_decode(chunk, spec[2]))
        else:
            raise ValueError(f"unknown kind {kind}")
    return out


# framework.proto schemas (field numbers cited in the module docstring)
_TENSOR_DESC = {1: ("data_type", "varint"),
                2: ("dims", "repeated_varint")}
_LOD_TENSOR_DESC = {1: ("tensor", "message", _TENSOR_DESC),
                    2: ("lod_level", "varint")}
_VAR_TYPE = {1: ("type", "varint"),
             2: ("selected_rows", "message", _TENSOR_DESC),
             3: ("lod_tensor", "message", _LOD_TENSOR_DESC)}
_VAR_DESC = {1: ("name", "string"),
             2: ("type", "message", _VAR_TYPE),
             3: ("persistable", "varint")}
_OP_VAR = {1: ("parameter", "string"),
           2: ("arguments", "repeated_string")}
_OP_ATTR = {1: ("name", "string"), 2: ("type", "varint"),
            3: ("i", "varint"), 4: ("f", "float"), 5: ("s", "string"),
            6: ("ints", "repeated_varint"),
            7: ("floats", "repeated_float"),
            8: ("strings", "repeated_string"),
            10: ("b", "varint"), 11: ("bools", "repeated_varint"),
            12: ("block_idx", "varint"), 13: ("l", "varint"),
            15: ("longs", "repeated_varint"),
            16: ("float64s", "repeated_double")}
_OP_DESC = {1: ("inputs", "repeated_message", _OP_VAR),
            2: ("outputs", "repeated_message", _OP_VAR),
            3: ("type", "string"),
            4: ("attrs", "repeated_message", _OP_ATTR)}
_BLOCK_DESC = {1: ("idx", "varint"), 2: ("parent_idx", "varint"),
               3: ("vars", "repeated_message", _VAR_DESC),
               4: ("ops", "repeated_message", _OP_DESC)}
_PROGRAM_DESC = {1: ("blocks", "repeated_message", _BLOCK_DESC),
                 4: ("version", "message", {1: ("version", "varint")})}

# AttrType enum (framework.proto:25)
ATTR_KINDS = {0: "i", 1: "f", 2: "s", 3: "ints", 4: "floats",
              5: "strings", 6: "b", 7: "bools", 8: "block_idx", 9: "l",
              10: "blocks_idx", 11: "longs", 12: "float64s"}

# VarType.Type data types (framework.proto:106)
DTYPES = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
          4: np.float16, 5: np.float32, 6: np.float64,
          20: np.uint8, 21: np.int8}


def _signed(v: int) -> int:
    """proto int32/int64 varints are two's-complement."""
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_program_desc(data: bytes) -> Dict[str, Any]:
    """ProgramDesc bytes → {"blocks": [{"vars": {...}, "ops": [...]}]}."""
    raw = _decode(memoryview(data), _PROGRAM_DESC)
    blocks = []
    for b in raw.get("blocks", []):
        vars_by_name = {}
        for v in b.get("vars", []):
            vt = v.get("type", {})
            lod = vt.get("lod_tensor", {})
            td = lod.get("tensor", {})
            vars_by_name[v["name"]] = {
                "persistable": bool(v.get("persistable", 0)),
                "type": vt.get("type"),
                "dtype": DTYPES.get(td.get("data_type", 5), np.float32),
                "shape": [_signed(d) for d in td.get("dims", [])],
            }
        ops = []
        for o in b.get("ops", []):
            attrs = {}
            for a in o.get("attrs", []):
                kind = ATTR_KINDS.get(a.get("type"))
                if kind is None:
                    continue
                val = a.get(kind)
                if kind in ("i", "l"):
                    val = _signed(val) if val is not None else 0
                elif kind in ("ints", "longs"):
                    val = [_signed(x) for x in (val or [])]
                elif kind == "b":
                    val = bool(val)
                elif kind == "bools":
                    val = [bool(x) for x in (val or [])]
                elif kind in ("floats", "strings", "float64s"):
                    val = val or []
                attrs[a["name"]] = val
            ops.append({
                "type": o["type"],
                "inputs": {i["parameter"]: i.get("arguments", [])
                           for i in o.get("inputs", [])},
                "outputs": {i["parameter"]: i.get("arguments", [])
                            for i in o.get("outputs", [])},
                "attrs": attrs,
            })
        blocks.append({"idx": b.get("idx", 0), "vars": vars_by_name,
                       "ops": ops})
    return {"blocks": blocks}


# ---------------------------------------------------------------------------
# LoDTensor / save_combine streams
# ---------------------------------------------------------------------------

def read_lod_tensor_stream(f) -> np.ndarray:
    """One LoDTensor record (lod_tensor.cc:244 + tensor_util.cc
    TensorToStream)."""
    _version = struct.unpack("<I", f.read(4))[0]
    lod_level = struct.unpack("<Q", f.read(8))[0]
    for _ in range(lod_level):
        n = struct.unpack("<Q", f.read(8))[0]
        f.read(n)
    _tversion = struct.unpack("<I", f.read(4))[0]
    desc_size = struct.unpack("<i", f.read(4))[0]
    desc = _decode(memoryview(f.read(desc_size)), _TENSOR_DESC)
    dtype = DTYPES.get(desc.get("data_type", 5), np.float32)
    dims = [_signed(d) for d in desc.get("dims", [])]
    count = int(np.prod(dims)) if dims else 1
    data = f.read(count * np.dtype(dtype).itemsize)
    return np.frombuffer(data, dtype=dtype).reshape(dims).copy()


def read_combined_params(data: bytes, names: List[str]) -> Dict[str, np.ndarray]:
    """save_combine payload: LoDTensor streams back to back, in `names`
    order (save_combine_op.h:34)."""
    f = io.BytesIO(data)
    out = {}
    for n in names:
        out[n] = read_lod_tensor_stream(f)
    if f.read(1):
        raise ValueError("trailing bytes after the last combined param — "
                         "name list does not match the file")
    return out
