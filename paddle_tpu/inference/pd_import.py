"""Execute reference `.pdmodel` inference graphs (reference
`paddle/fluid/inference/api/analysis_predictor.h:82` Run → per-op
executor loop; `fluid/inference/io.cc` Load).

TPU redesign: instead of an op interpreter, the parsed ProgramDesc block
is bound op-by-op to this framework's jnp semantics and the WHOLE graph
is one `jax.jit` program (parameters closure-baked as constants so XLA
folds/fuses them). Covers the op vocabulary v2.0 save_inference_model
emits for MLP/CNN/transformer-encoder graphs; unmapped op types raise
UnimplementedError naming them."""
from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from .pd_format import parse_program_desc, read_combined_params

__all__ = ["LegacyInferenceModel", "load_legacy_inference_model"]


def _bcast_y(x, y, axis):
    """elementwise_* `axis` semantics (reference
    `operators/elementwise/elementwise_op_function.h`): align y's dims to
    x starting at `axis` (or from the right when axis == -1)."""
    import jax.numpy as jnp
    if x.ndim == y.ndim or y.ndim == 0:
        return y
    ax = axis if axis >= 0 else x.ndim - y.ndim
    shape = [1] * x.ndim
    for i, d in enumerate(y.shape):
        shape[ax + i] = d
    return jnp.reshape(y, shape)


def _build_op(op_type: str, attrs: Dict[str, Any]) -> Callable:
    """op type + attrs → fn(*input_arrays) -> tuple(output_arrays).
    Input order matches _op_input_order below."""
    import jax
    import jax.numpy as jnp

    a = attrs

    if op_type == "mul":
        xd = a.get("x_num_col_dims", 1)
        yd = a.get("y_num_col_dims", 1)

        def fn(x, y):
            xm = x.reshape(int(np.prod(x.shape[:xd])), -1)
            ym = y.reshape(int(np.prod(y.shape[:yd])), -1)
            out = xm @ ym
            return out.reshape(tuple(x.shape[:xd]) + tuple(y.shape[yd:]))
        return fn
    if op_type in ("matmul", "matmul_v2"):
        tx = a.get("transpose_X", a.get("trans_x", False))
        ty = a.get("transpose_Y", a.get("trans_y", False))
        alpha = a.get("alpha", 1.0)

        def fn(x, y):
            if tx:
                x = jnp.swapaxes(x, -1, -2)
            if ty:
                y = jnp.swapaxes(y, -1, -2)
            return jnp.matmul(x, y) * alpha
        return fn
    if op_type.startswith("elementwise_"):
        kind = op_type[len("elementwise_"):]
        base = {"add": jnp.add, "sub": jnp.subtract,
                "mul": jnp.multiply, "div": jnp.divide,
                "max": jnp.maximum, "min": jnp.minimum,
                "pow": jnp.power}[kind]
        axis = a.get("axis", -1)
        return lambda x, y: base(x, _bcast_y(x, y, axis))
    if op_type == "relu":
        return lambda x: jnp.maximum(x, 0)
    if op_type == "gelu":
        approx = a.get("approximate", False)
        return lambda x: jax.nn.gelu(x, approximate=bool(approx))
    if op_type == "sigmoid":
        return lambda x: jax.nn.sigmoid(x)
    if op_type == "tanh":
        return jnp.tanh
    if op_type == "exp":
        return jnp.exp
    if op_type == "sqrt":
        return jnp.sqrt
    if op_type == "softmax":
        ax = a.get("axis", -1)
        return lambda x: jax.nn.softmax(x, axis=ax)
    if op_type == "scale":
        s, b = a.get("scale", 1.0), a.get("bias", 0.0)
        after = a.get("bias_after_scale", True)
        return (lambda x: x * s + b) if after else (lambda x: (x + b) * s)
    if op_type in ("lookup_table_v2", "lookup_table"):
        pad = a.get("padding_idx", -1)

        def fn(w, ids):
            ids = ids.reshape(ids.shape[:-1]) \
                if op_type == "lookup_table" and ids.shape[-1] == 1 else ids
            out = jnp.take(w, ids, axis=0)
            if pad is not None and pad >= 0:
                out = jnp.where((ids == pad)[..., None], 0.0, out)
            return out
        return fn
    if op_type in ("reduce_mean", "reduce_sum", "reduce_max", "reduce_min"):
        red = {"reduce_mean": jnp.mean, "reduce_sum": jnp.sum,
               "reduce_max": jnp.max, "reduce_min": jnp.min}[op_type]
        dims = tuple(a.get("dim", [0]))
        keep = a.get("keep_dim", False)
        if a.get("reduce_all", False):
            return lambda x: red(x)
        return lambda x: red(x, axis=dims, keepdims=keep)
    if op_type in ("reshape2", "reshape"):
        shape = list(a.get("shape", []))

        def fn(x):
            tgt = [x.shape[i] if s == 0 else s
                   for i, s in enumerate(shape)]
            return x.reshape(tgt)
        return fn
    if op_type in ("transpose2", "transpose"):
        perm = a.get("axis", [])
        return lambda x: jnp.transpose(x, perm)
    if op_type == "concat":
        ax = a.get("axis", 0)
        return lambda *xs: jnp.concatenate(xs, axis=ax)
    if op_type == "stack":
        ax = a.get("axis", 0)
        return lambda *xs: jnp.stack(xs, axis=ax)
    if op_type == "dropout":
        return lambda x: x          # inference graphs run is_test=True
    if op_type == "cast":
        from .pd_format import DTYPES
        out_dt = DTYPES.get(a.get("out_dtype", 5), np.float32)
        return lambda x: x.astype(out_dt)
    if op_type == "batch_norm":
        eps = a.get("epsilon", 1e-5)

        def fn(x, scale, bias, mean, var):
            sh = (1, -1) + (1,) * (x.ndim - 2)
            return (x - mean.reshape(sh)) / jnp.sqrt(
                var.reshape(sh) + eps) * scale.reshape(sh) + \
                bias.reshape(sh)
        return fn
    if op_type == "layer_norm":
        eps = a.get("epsilon", 1e-5)
        bna = a.get("begin_norm_axis", 1)

        def fn(x, scale, bias):
            axes = tuple(range(bna, x.ndim))
            m = jnp.mean(x, axis=axes, keepdims=True)
            v = jnp.var(x, axis=axes, keepdims=True)
            y = (x - m) / jnp.sqrt(v + eps)
            sh = (1,) * bna + tuple(x.shape[bna:])
            return y * scale.reshape(sh) + bias.reshape(sh)
        return fn
    if op_type in ("conv2d", "depthwise_conv2d"):
        strides = tuple(a.get("strides", [1, 1]))
        pads = a.get("paddings", [0, 0])
        dil = tuple(a.get("dilations", [1, 1]))
        groups = a.get("groups", 1)
        if len(pads) == 2:
            pads = [(pads[0], pads[0]), (pads[1], pads[1])]
        else:
            pads = [(pads[0], pads[1]), (pads[2], pads[3])]

        def fn(x, w):
            return jax.lax.conv_general_dilated(
                x, w, strides, pads, rhs_dilation=dil,
                feature_group_count=groups,
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return fn
    if op_type == "pool2d":
        ptype = a.get("pooling_type", "max")
        ks = tuple(a.get("ksize", [2, 2]))
        strides = tuple(a.get("strides", ks))
        pads = a.get("paddings", [0, 0])
        exclusive = a.get("exclusive", True)
        if a.get("global_pooling", False):
            if ptype == "max":
                return lambda x: jnp.max(x, axis=(2, 3), keepdims=True)
            return lambda x: jnp.mean(x, axis=(2, 3), keepdims=True)
        pad4 = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))

        def fn(x):
            import jax.lax as lax
            if ptype == "max":
                return lax.reduce_window(
                    x, -jnp.inf, lax.max, (1, 1) + ks,
                    (1, 1) + strides, pad4)
            s = lax.reduce_window(x, 0.0, lax.add, (1, 1) + ks,
                                  (1, 1) + strides, pad4)
            if exclusive:
                # reference default: divide by the VALID cell count at
                # the borders (pool_op.h exclusive=True)
                ones = jnp.ones_like(x)
                cnt = lax.reduce_window(ones, 0.0, lax.add, (1, 1) + ks,
                                        (1, 1) + strides, pad4)
                return s / cnt
            return s / (ks[0] * ks[1])
        return fn
    if op_type in ("squeeze2", "squeeze"):
        axes = tuple(a.get("axes", []))
        return lambda x: jnp.squeeze(x, axis=axes or None)
    if op_type in ("unsqueeze2", "unsqueeze"):
        axes = a.get("axes", [])

        def fn(x):
            for ax in sorted(axes):
                x = jnp.expand_dims(x, ax)
            return x
        return fn
    if op_type == "slice":
        axes = a.get("axes", [])
        starts = a.get("starts", [])
        ends = a.get("ends", [])
        dec = a.get("decrease_axis", [])

        def fn(x):
            idx = [slice(None)] * x.ndim
            for ax, s, e in zip(axes, starts, ends):
                idx[ax] = slice(s, e)
            out = x[tuple(idx)]
            if dec:   # x[0]-style indexing drops the size-1 dims
                out = jnp.squeeze(out, axis=tuple(dec))
            return out
        return fn
    if op_type == "assign":
        return lambda x: x
    if op_type == "arg_max":
        ax = a.get("axis", -1)
        return lambda x: jnp.argmax(x, axis=ax).astype(np.int64)
    if op_type == "fill_constant":
        from .pd_format import DTYPES
        shape = a.get("shape", [])
        dt = DTYPES.get(a.get("dtype", 5), np.float32)
        val = a.get("value", 0.0)
        return lambda: jnp.full(shape, val, dt)
    raise NotImplementedError(
        f"reference op type {op_type!r} has no mapping yet "
        f"(inference/pd_import.py)")


# slot order each op's impl expects (reference OpDesc input parameters)
_INPUT_ORDER = {
    "mul": ["X", "Y"], "matmul": ["X", "Y"], "matmul_v2": ["X", "Y"],
    "lookup_table_v2": ["W", "Ids"], "lookup_table": ["W", "Ids"],
    "batch_norm": ["X", "Scale", "Bias", "Mean", "Variance"],
    "layer_norm": ["X", "Scale", "Bias"],
    "conv2d": ["Input", "Filter"], "depthwise_conv2d": ["Input", "Filter"],
}
_OUTPUT_SLOT = {"batch_norm": "Y", "layer_norm": "Y", "conv2d": "Output",
                "depthwise_conv2d": "Output", "pool2d": "Out"}


class LegacyInferenceModel:
    """A loaded reference inference program, compiled as one XLA program."""

    def __init__(self, program: Dict, params: Dict[str, np.ndarray]):
        import jax

        block = program["blocks"][0]
        self.feed_names: List[str] = []
        self.fetch_names: List[str] = []
        steps = []
        for op in block["ops"]:
            t = op["type"]
            if t == "feed":
                self.feed_names.append(op["outputs"]["Out"][0])
                continue
            if t == "fetch":
                self.fetch_names.append(op["inputs"]["X"][0])
                continue
            fn = _build_op(t, op["attrs"])
            order = _INPUT_ORDER.get(t)
            if order:
                in_names = [op["inputs"][k][0] for k in order
                            if op["inputs"].get(k)]
            else:
                xs = op["inputs"].get("X", [])
                ys = op["inputs"].get("Y", [])
                in_names = list(xs) + list(ys)
            out_slot = _OUTPUT_SLOT.get(t, "Out")
            out_name = op["outputs"][out_slot][0]
            steps.append((t, fn, in_names, out_name))
        self._steps = steps
        self._params = {k: np.asarray(v) for k, v in params.items()}

        def run_fn(feeds: List):
            env = dict(self._params)
            env.update(zip(self.feed_names, feeds))
            for t, fn, in_names, out_name in self._steps:
                env[out_name] = fn(*[env[n] for n in in_names])
            return [env[n] for n in self.fetch_names]
        self._jit = jax.jit(run_fn)

    def run(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        import jax.numpy as jnp
        feeds = [jnp.asarray(np.asarray(feed[n])) for n in self.feed_names]
        return [np.asarray(o) for o in self._jit(feeds)]


def load_legacy_inference_model(model_path: str,
                                params_path: str = None
                                ) -> LegacyInferenceModel:
    """Load reference `.pdmodel` (+ combined `.pdiparams`).

    Param order in the combined file follows sorted persistable-var names
    (`fluid/io.py` save_vars sorts by name before save_combine)."""
    with open(model_path, "rb") as f:
        program = parse_program_desc(f.read())
    params: Dict[str, np.ndarray] = {}
    if params_path:
        names = sorted(n for n, v in program["blocks"][0]["vars"].items()
                       if v["persistable"])
        with open(params_path, "rb") as f:
            params = read_combined_params(f.read(), names)
    return LegacyInferenceModel(program, params)
