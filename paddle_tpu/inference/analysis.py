"""Inference analysis pipeline (reference `inference/analysis/`:
`Analyzer` running ordered `AnalysisPass`es over an `Argument`, and the
TensorRT/Lite subgraph engines of `analysis/ir_passes/`).

TPU redesign: the heavy fusion work is XLA's; what the Analyzer does here
is the *structural* part of the reference pipeline — load a serialized
Program, fold/prune it, and cluster op ranges into pre-compiled ENGINE
ops. An engine op is the Lite/TensorRT analogue: a contiguous sub-DAG of
the Program replaced by ONE op whose body is a separately `jax.jit`-
compiled callable of the fused slice (reference
`operators/lite/lite_engine_op.h`, `tensorrt_engine_op.h`).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Argument", "Analyzer", "AnalysisPass", "register_analysis_pass",
           "engine_subgraph_pass", "compile_subgraph_engine"]


class Argument:
    """Pass pipeline state (reference `analysis/argument.h` — a typed
    property bag handed from pass to pass)."""

    def __init__(self, program=None, scope=None, fetch_targets=None,
                 model_path=None):
        self.program = program
        self.scope = scope if scope is not None else {}
        self.fetch_targets = fetch_targets
        self.model_path = model_path
        self.engine_ops: List[int] = []    # indices of fused engine ops


_ANALYSIS_PASSES: Dict[str, Callable[[Argument], None]] = {}


def register_analysis_pass(name: str):
    def deco(fn):
        _ANALYSIS_PASSES[name] = fn
        return fn
    return deco


class AnalysisPass:
    """Callable wrapper so passes can also be used/extended OO-style
    (reference `analysis/analysis_pass.h`)."""

    def __init__(self, name: str):
        self.name = name

    def run(self, argument: Argument):
        _ANALYSIS_PASSES[self.name](argument)


@register_analysis_pass("ir_graph_build_pass")
def _ir_graph_build(arg: Argument):
    """Load the serialized Program (reference ir_graph_build_pass reads
    the ProgramDesc)."""
    if arg.program is None:
        from ..static.program import Program
        arg.program, params = Program.load(arg.model_path)
        arg.scope.update(params)


@register_analysis_pass("ir_analysis_pass")
def _ir_analysis(arg: Argument):
    """Constant folding (reference runs the selected ir fusion passes;
    fusion itself is XLA's at compile time)."""
    from ..static.passes import get_pass
    get_pass("constant_folding_pass")(arg.program)


@register_analysis_pass("memory_optimize_pass")
def _memory_optimize(arg: Argument):
    """Dead-code elimination against the fetch targets (reference
    memory_optimize_pass reuses buffers; XLA owns buffers here, so the
    memory lever at this level is dropping dead ops/vars)."""
    if arg.fetch_targets:
        from ..static.passes import get_pass
        get_pass("dead_code_elimination_pass")(arg.program,
                                               targets=arg.fetch_targets)


@register_analysis_pass("engine_subgraph_pass")
def engine_subgraph_pass(arg: Argument):
    """Cluster the largest fusable contiguous op range into one engine op
    (reference tensorrt_subgraph_pass / lite_subgraph_pass mark maximal
    subgraphs and replace them with engine ops)."""
    prog = arg.program
    if len(prog.ops) >= 2:
        fetch = [t.slot for t in (arg.fetch_targets or [])
                 if hasattr(t, "slot")]
        idx = compile_subgraph_engine(prog, 0, len(prog.ops),
                                      fetch_slots=fetch)
        arg.engine_ops.append(idx)


@register_analysis_pass("ir_graph_to_program_pass")
def _ir_graph_to_program(arg: Argument):
    """Terminal no-op: the Program IS the executable representation
    (reference converts the ir::Graph back to a ProgramDesc)."""


def compile_subgraph_engine(program, start: int, stop: int,
                            engine_type: str = "xla",
                            fetch_slots: Sequence[int] = ()) -> int:
    """Replace program.ops[start:stop] with ONE pre-compiled engine op.

    The slice's external inputs/outputs are computed from slot liveness;
    the engine body is a jax.jit-compiled replay of the slice — the exact
    contract of the reference's engine ops (feed the subgraph's inputs,
    run the foreign engine, fetch its outputs). Returns the index of the
    engine op in the rewritten op list.
    """
    import jax

    from ..static.program import _Op

    ops = program.ops
    slice_ops = ops[start:stop]
    produced = {s for op in slice_ops for s in op.out_slots}
    ext_in: List[int] = []
    for op in slice_ops:
        for tag, ref in op.in_refs:
            if tag == "s" and ref not in produced and ref not in ext_in:
                ext_in.append(ref)
    # outputs: slice-produced slots consumed by later ops or fetched;
    # with neither known, every produced slot stays fetchable
    used_later = {ref for op in ops[stop:] for tag, ref in op.in_refs
                  if tag == "s"}
    keep = used_later | set(fetch_slots)
    out_slots = sorted(produced & keep) if produced & keep \
        else sorted(produced)

    def engine_body(*ext_vals):
        env = dict(zip(ext_in, ext_vals))
        for op in slice_ops:
            args = []
            for tag, ref in op.in_refs:
                if tag == "c":
                    args.append(ref)
                elif ref in env:
                    args.append(env[ref])
                else:
                    args.append(program.vars[ref]._value)
            outs = op.fn(*args)
            outs = outs if isinstance(outs, (tuple, list)) else (outs,)
            for s, o in zip(op.out_slots, outs):
                env[s] = o
        return tuple(env[s] for s in out_slots)

    compiled = jax.jit(engine_body)
    engine = _Op(f"{engine_type}_engine", compiled,
                 [("s", s) for s in ext_in], list(out_slots),
                 {"engine_type": engine_type,
                  "fused_op_types": [op.name for op in slice_ops],
                  "num_fused_ops": len(slice_ops)})
    program.ops = ops[:start] + [engine] + ops[stop:]
    return start


class Analyzer:
    """Ordered pass driver (reference `analysis/analyzer.cc:Analyzer::
    RunAnalysis`)."""

    DEFAULT_PASSES = ["ir_graph_build_pass", "ir_analysis_pass",
                      "memory_optimize_pass", "engine_subgraph_pass",
                      "ir_graph_to_program_pass"]

    def __init__(self, passes: Optional[Sequence[str]] = None):
        self.passes = list(passes if passes is not None
                           else self.DEFAULT_PASSES)

    def run(self, argument: Argument) -> Argument:
        for name in self.passes:
            if name not in _ANALYSIS_PASSES:
                from ..framework.errors import NotFoundError
                raise NotFoundError(f"unknown analysis pass {name!r}; "
                                    f"have {sorted(_ANALYSIS_PASSES)}")
            _ANALYSIS_PASSES[name](argument)
        return argument
