"""Inference stack (reference `paddle/fluid/inference/`:
AnalysisPredictor:82, AnalysisConfig, zero-copy tensors, pass pipeline).

TPU-native: the serving artifact is the StableHLO export written by
`jit.save` (.pdmodel) + weights (.pdiparams). "Analysis passes" (fusion,
memory optimize) are XLA's job at artifact-compile time; the predictor
deserializes once, compiles once per shape, and runs zero-copy on device
buffers. API mirrors `paddle.inference`: Config / create_predictor /
get_input_handle / run / get_output_handle.
"""
from __future__ import annotations

import contextlib
import os
import weakref
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "create_predictor", "Predictor", "PredictorTensor",
           "AnalysisConfig", "Analyzer", "Argument",
           "compile_subgraph_engine", "format_input_sig", "check_fed_input",
           "as_device", "resolve_devices"]

from .analysis import Analyzer, Argument, compile_subgraph_engine  # noqa: E402

# STAT_quant_weight_hbm_bytes gauges device-resident quantized-weight
# bytes across LIVE predictor replicas: each replica gauge_add()s its
# integer tensors on load and subtracts them when it is collected
# (weakref.finalize — Predictor has no explicit close; CPython refcount
# collection makes this prompt in practice), so the gauge tracks actual
# residency instead of growing monotonically across engine restarts.
def _note_quant_bytes(delta: int) -> None:
    from ..framework import monitor
    monitor.stat_gauge_add("STAT_quant_weight_hbm_bytes", delta)


def _same_buffer(a, b) -> bool:
    """Do two jax Arrays share one device buffer? (device_put onto the
    buffer's current device aliases instead of copying — distinct Array
    objects, same memory.)"""
    if a is b:
        return True
    try:
        return a.unsafe_buffer_pointer() == b.unsafe_buffer_pointer()
    except Exception:  # backends without buffer introspection
        return False


class Config:
    """reference `api/paddle_analysis_config.h`."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._use_accel = True
        self._threads = 1
        self._enable_profile = False
        self._memory_pool_mb = 0
        self.set_model(prog_file, params_file)

    def set_model(self, prog_file, params_file=None):
        """Update only the model/params paths. (Historically this re-ran
        __init__, silently resetting user-set options like `_threads`,
        `_enable_profile` and `_memory_pool_mb` — reference
        AnalysisConfig::SetModel only touches the paths.)"""
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_path = prog_file
        self.params_file = params_file

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_accel = True
        self._memory_pool_mb = memory_pool_init_size_mb

    def enable_use_tpu(self, device_id=0):
        self._use_accel = True

    def disable_gpu(self):
        self._use_accel = False

    def use_gpu(self):
        return self._use_accel

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    def enable_profile(self):
        self._enable_profile = True

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes

    def enable_memory_optim(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        import warnings
        warnings.warn("TensorRT does not exist on TPU; the XLA-compiled "
                      "artifact is already the fused engine")

    def summary(self):
        return f"Config(model={self.model_path}, accel={self._use_accel})"


AnalysisConfig = Config


class PredictorTensor:
    """Zero-copy handle (reference zero-copy PaddleTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass

    def copy_from_cpu(self, arr: np.ndarray):
        import jax.numpy as jnp
        self._value = jnp.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


def format_input_sig(name, dims, dtype):
    """'name: dtype[b,8]' rendering of one saved-signature entry (symbolic
    dims print as 'b')."""
    if dims is None:
        return str(name)
    ds = ",".join("b" if d is None else str(d) for d in dims)
    return f"{name}: {np.dtype(dtype).name if dtype is not None else '?'}[{ds}]"


def check_fed_input(arr, name, dims, dtype, *, skip_batch_dim=False,
                    ctx="Predictor.run", expect=""):
    """Shared rank/dim/dtype check for one fed array — used by both
    Predictor.run and serving.InferenceEngine.submit so validation and
    error wording never drift apart. Returns the array (same-kind-cast to
    the saved dtype when needed) or raises a ValueError naming the
    expected signature."""
    note = f"; model signature is [{expect}]" if expect else ""
    if dims is not None:
        if arr.ndim != len(dims):
            raise ValueError(
                f"{ctx}: input {name!r} expects rank {len(dims)} "
                f"({format_input_sig(name, dims, dtype)}) but got rank "
                f"{arr.ndim} with shape {tuple(arr.shape)}{note}")
        for axis, (want, got) in enumerate(zip(dims, arr.shape)):
            if skip_batch_dim and axis == 0:
                continue
            if want is not None and int(want) != int(got):
                raise ValueError(
                    f"{ctx}: input {name!r} dim {axis} must be {want} but "
                    f"got {got} (shape {tuple(arr.shape)}; expected "
                    f"{format_input_sig(name, dims, dtype)}){note}")
    if dtype is not None and np.dtype(arr.dtype) != np.dtype(dtype):
        if not np.can_cast(arr.dtype, dtype, casting="same_kind"):
            raise ValueError(
                f"{ctx}: input {name!r} expects dtype "
                f"{np.dtype(dtype).name} but got {np.dtype(arr.dtype).name}"
                f" (not safely castable){note}")
        arr = np.asarray(arr, dtype=dtype)
    return arr


def as_device(dev):
    """Canonicalize one device spec: an int is an index into
    `jax.local_devices()`; a jax Device passes through."""
    if isinstance(dev, (int, np.integer)):
        import jax
        local = jax.local_devices()
        if not 0 <= int(dev) < len(local):
            raise ValueError(f"device index {dev} out of range; host has "
                             f"{len(local)} local device(s)")
        return local[int(dev)]
    return dev


def resolve_devices(devices):
    """Expand a device-set spec into a list of jax Devices. Accepts
    'all' (every local device), an int count (first N local devices), a
    comma-separated index string ('0,2'), or a sequence of indices /
    Devices. The serving engine builds one Predictor replica (and one
    dispatch lane) per entry."""
    import jax
    local = jax.local_devices()
    if isinstance(devices, str):
        if devices.strip().lower() == "all":
            return list(local)
        devices = [int(x) for x in devices.split(",") if x.strip()]
    elif isinstance(devices, (int, np.integer)):
        n = int(devices)
        if not 1 <= n <= len(local):
            raise ValueError(f"asked for {n} serving device(s) but host "
                             f"has {len(local)}")
        return list(local[:n])
    out = [as_device(d) for d in devices]
    if not out:
        raise ValueError("empty device list")
    return out


class Predictor:
    def __init__(self, config: Config, device=None):
        import jax
        from .. import jit
        self._config = config
        self._device = as_device(device) if device is not None else None
        self._legacy = None
        if config.model_path is None:
            raise ValueError("Config has no model path")
        try:
            self._translated = jit.load(config.model_path)
            self._quant = self._translated._quant
            self._qargs = self._load_quant_args()
            nin = len(self._translated._exported.in_avals) \
                - len(self._qargs)
            self._input_names = [f"input_{i}" for i in range(nin)]
        except Exception as stablehlo_err:
            # not our StableHLO artifact — try the reference ProgramDesc
            # format (.pdmodel + combined .pdiparams; pd_import.py)
            from .pd_import import load_legacy_inference_model
            model_file = config.model_path + ".pdmodel"
            if not os.path.exists(model_file):
                raise
            params_file = config.params_file
            if params_file is None:
                cand = config.model_path + ".pdiparams"
                params_file = cand if os.path.exists(cand) else None
            try:
                self._legacy = load_legacy_inference_model(model_file,
                                                           params_file)
            except Exception as legacy_err:
                raise RuntimeError(
                    f"{model_file} is neither a loadable StableHLO "
                    f"artifact ({stablehlo_err!r}) nor a parseable "
                    f"reference ProgramDesc ({legacy_err!r})"
                ) from legacy_err
            self._translated = None
            self._quant = None
            self._qargs = []
            self._input_names = list(self._legacy.feed_names)
        self._inputs: Dict[str, PredictorTensor] = {}
        self._outputs: List[PredictorTensor] = []
        for n in self._input_names:
            self._inputs[n] = PredictorTensor(n)
        self._jit_call = None
        self._sig = None
        self._sig_str = ""
        import threading
        self._jit_lock = threading.Lock()
        # exact per-predictor XLA compile count (bumped at jit trace time;
        # Python side effects run once per trace = once per new signature)
        self.compile_count = 0

    @property
    def device(self):
        """The jax Device this predictor is pinned to (None = backend
        default). Pinning happens at dispatch via `jax.default_device`,
        so fed host arrays land — and the executable compiles — there."""
        return self._device

    # -- quantized artifacts ----------------------------------------------

    def _load_quant_args(self):
        """Device-resident integer weights for a quantized artifact: the
        .pdmeta manifest names the int8/packed-int4 tensors + scales the
        export expects as leading runtime arguments. They are uploaded
        ONCE per replica (to this predictor's device) and stay in
        integer form in HBM — the dequant is inside the compiled call,
        fused into the matmul, so no fp32 copy of any quantized weight
        ever materializes host- or device-side."""
        if not self._quant:
            return []
        import jax
        from ..framework import monitor
        qargs = [jax.device_put(v, self._device)
                 for v in self._translated._qargs]
        monitor.stat_add("STAT_quant_weights_loaded",
                         len(self._quant["entries"]))
        # gauge only buffers this replica's device_put actually CREATED:
        # a put onto the buffer's current device aliases it (same
        # underlying buffer, no new HBM), so a same-device replica adds
        # 0 and a cross-device replica adds its full copy — the base
        # materialization itself is accounted once by TranslatedLayer
        total = sum(int(a.nbytes) for a, v in
                    zip(qargs, self._translated._qargs)
                    if not _same_buffer(a, v))
        if total:
            _note_quant_bytes(total)
            # LIVE residency: subtract when this replica is collected
            # (its device buffers go with it)
            weakref.finalize(self, _note_quant_bytes, -total)
        return qargs

    def quant_info(self) -> Optional[dict]:
        """None for fp artifacts; else {bits histogram, device-resident
        integer bytes, tensor count} — surfaced by engine.stats()."""
        if not self._quant:
            return None
        bits = {}
        for e in self._quant["entries"]:
            bits[str(e["bits"])] = bits.get(str(e["bits"]), 0) + 1
        return {"weight_tensors": len(self._quant["entries"]),
                "bits": bits,
                "resident_bytes": sum(int(a.nbytes)
                                      for a in self._qargs)}

    def clone_for_device(self, device) -> "Predictor":
        """Replica on another device sharing the already-deserialized
        artifact (no disk re-load) but with its OWN cached jit wrapper,
        trace counter, and I/O handles. Serving lanes need one replica
        per device precisely because a `jax.jit` executable is per-device
        state: a fresh wrapper per replica keeps `compile_count` an exact
        per-(device, bucket) compile ledger."""
        import copy as _copy
        import threading
        p = _copy.copy(self)
        p._device = as_device(device) if device is not None else None
        p._inputs = {n: PredictorTensor(n) for n in self._input_names}
        p._outputs = []
        p._jit_call = None
        p._jit_lock = threading.Lock()
        p.compile_count = 0
        # integer weights are per-device state: each replica uploads its
        # own copy to its chip (same int8/int4 bytes, new residence)
        p._qargs = p._load_quant_args()
        return p

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    # -- saved signature ---------------------------------------------------

    def input_signature(self):
        """[(name, dims, dtype)] from the saved artifact; symbolic dims
        (shape-polymorphic exports) are None. Legacy ProgramDesc artifacts
        carry no aval info → dims/dtype are None. Immutable → built once
        (run() revalidates every request against it)."""
        if self._sig is not None:
            return self._sig
        if self._translated is None:
            sig = [(n, None, None) for n in self._input_names]
        else:
            sig = []
            # a quantized artifact's leading avals are its integer
            # weights + scales (fed by the predictor, not the caller)
            user_avals = self._translated._exported.in_avals[
                len(self._qargs):]
            for n, aval in zip(self._input_names, user_avals):
                dims = tuple(d if isinstance(d, int) else None
                             for d in aval.shape)
                sig.append((n, dims, np.dtype(aval.dtype)))
        self._sig = sig
        self._sig_str = ", ".join(format_input_sig(*s) for s in sig)
        return sig

    def _validate_feed(self, arrays):
        """Check fed arrays against the saved signature; raise a ValueError
        naming the expected inputs instead of failing deep inside JAX."""
        sig = self.input_signature()
        expect = self._sig_str
        if len(arrays) != len(sig):
            raise ValueError(
                f"Predictor.run: model expects {len(sig)} input(s) "
                f"[{expect}] but {len(arrays)} were fed")
        out = []
        for a, (name, dims, dtype) in zip(arrays, sig):
            if a is None:
                raise ValueError(
                    f"Predictor.run: input {name!r} was never fed "
                    f"(expected [{expect}]; use get_input_handle"
                    f"({name!r}).copy_from_cpu(...) or pass inputs=)")
            arr = np.asarray(a) if not hasattr(a, "dtype") else a
            out.append(check_fed_input(arr, name, dims, dtype,
                                       ctx="Predictor.run", expect=expect))
        return out

    # -- compiled zero-copy path ------------------------------------------

    def _get_jit_call(self):
        """One jax.jit wrapper around the deserialized executable, cached
        on the predictor: repeat runs (and every serving-engine dispatch)
        reuse the compiled-per-shape executable zero-copy instead of
        re-dispatching `exported.call` eagerly. The trace-time counter
        bump makes STAT_predictor_compiles an exact compile count (Python
        side effects run once per trace = once per new input signature)."""
        if self._jit_call is None:
            with self._jit_lock:  # concurrent first runs must not build
                if self._jit_call is not None:  # two wrappers (= two traces
                    return self._jit_call       # per shape, breaking the
                import jax                      # exact-compile-count contract)
                from ..device import maybe_enable_compilation_cache
                from ..framework import monitor
                # resolve the deferred persistent-cache decision: a
                # serving-only process never passes through functionalize(),
                # so the first predictor compile is its "first framework
                # compile" (device/__init__.py contract)
                maybe_enable_compilation_cache()
                exported = self._translated._exported

                def _call(*args):
                    monitor.stat_add("STAT_predictor_compiles")
                    self.compile_count += 1
                    return exported.call(*args)
                self._jit_call = jax.jit(_call)
        return self._jit_call

    def run_device(self, arrays):
        """Run on already-validated arrays; returns device-resident output
        leaves (no host round-trip, and no host sync — under JAX async
        dispatch the leaves are futures the caller blocks on). The serving
        engine's lane-dispatch hot path."""
        import jax
        ctx = (jax.default_device(self._device) if self._device is not None
               else contextlib.nullcontext())
        with ctx:
            if self._legacy is not None:
                out = self._legacy.run(dict(zip(self._input_names, arrays)))
            else:
                # quantized artifacts: the device-resident integer
                # weights ride every dispatch as leading jit ARGUMENTS —
                # being runtime inputs (not baked constants) is what
                # stops XLA from dequant-folding them to fp32 in HBM
                out = self._get_jit_call()(*self._qargs, *arrays)
        return jax.tree_util.tree_leaves(out)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        import jax
        if inputs is not None:
            # validate BEFORE touching the handles: a rejected call must
            # not leave half-fed state behind
            args = self._validate_feed([np.asarray(a) for a in inputs])
            # upload under the pin so the host array lands directly on
            # this predictor's device instead of hopping via the default
            ctx = (jax.default_device(self._device)
                   if self._device is not None else contextlib.nullcontext())
            with ctx:
                for n, a in zip(self._input_names, args):
                    self._inputs[n].copy_from_cpu(a)
            # compute from the device-resident handle values so the upload
            # copy_from_cpu just did is the only host→device transfer
            args = [self._inputs[n]._value for n in self._input_names]
        else:
            args = self._validate_feed(
                [self._inputs[n]._value for n in self._input_names])
        leaves = self.run_device(args)
        self._outputs = []
        for i, leaf in enumerate(leaves):
            t = PredictorTensor(f"output_{i}")
            t._value = leaf
            self._outputs.append(t)
        if inputs is not None:
            return [np.asarray(o._value) for o in self._outputs]
        return True

    def get_output_names(self):
        return [t.name for t in self._outputs] or ["output_0"]

    def get_output_handle(self, name):
        idx = int(name.rsplit("_", 1)[1])
        return self._outputs[idx]


def create_predictor(config: Config, device=None) -> Predictor:
    """Build a Predictor; `device` (jax Device or local index) pins its
    compilation and execution to one chip — `serving.InferenceEngine`
    passes a different device per dispatch lane."""
    return Predictor(config, device=device)
