"""Inference stack (reference `paddle/fluid/inference/`:
AnalysisPredictor:82, AnalysisConfig, zero-copy tensors, pass pipeline).

TPU-native: the serving artifact is the StableHLO export written by
`jit.save` (.pdmodel) + weights (.pdiparams). "Analysis passes" (fusion,
memory optimize) are XLA's job at artifact-compile time; the predictor
deserializes once, compiles once per shape, and runs zero-copy on device
buffers. API mirrors `paddle.inference`: Config / create_predictor /
get_input_handle / run / get_output_handle.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "create_predictor", "Predictor", "PredictorTensor",
           "AnalysisConfig", "Analyzer", "Argument",
           "compile_subgraph_engine"]

from .analysis import Analyzer, Argument, compile_subgraph_engine  # noqa: E402


class Config:
    """reference `api/paddle_analysis_config.h`."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_path = prog_file
        self.params_file = params_file
        self._use_accel = True
        self._threads = 1
        self._enable_profile = False
        self._memory_pool_mb = 0

    def set_model(self, prog_file, params_file=None):
        self.__init__(prog_file, params_file)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_accel = True
        self._memory_pool_mb = memory_pool_init_size_mb

    def enable_use_tpu(self, device_id=0):
        self._use_accel = True

    def disable_gpu(self):
        self._use_accel = False

    def use_gpu(self):
        return self._use_accel

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    def enable_profile(self):
        self._enable_profile = True

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes

    def enable_memory_optim(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        import warnings
        warnings.warn("TensorRT does not exist on TPU; the XLA-compiled "
                      "artifact is already the fused engine")

    def summary(self):
        return f"Config(model={self.model_path}, accel={self._use_accel})"


AnalysisConfig = Config


class PredictorTensor:
    """Zero-copy handle (reference zero-copy PaddleTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass

    def copy_from_cpu(self, arr: np.ndarray):
        import jax.numpy as jnp
        self._value = jnp.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    def __init__(self, config: Config):
        import jax
        from .. import jit
        self._config = config
        self._legacy = None
        if config.model_path is None:
            raise ValueError("Config has no model path")
        try:
            self._translated = jit.load(config.model_path)
            nin = len(self._translated._exported.in_avals)
            self._input_names = [f"input_{i}" for i in range(nin)]
        except Exception as stablehlo_err:
            # not our StableHLO artifact — try the reference ProgramDesc
            # format (.pdmodel + combined .pdiparams; pd_import.py)
            from .pd_import import load_legacy_inference_model
            model_file = config.model_path + ".pdmodel"
            if not os.path.exists(model_file):
                raise
            params_file = config.params_file
            if params_file is None:
                cand = config.model_path + ".pdiparams"
                params_file = cand if os.path.exists(cand) else None
            try:
                self._legacy = load_legacy_inference_model(model_file,
                                                           params_file)
            except Exception as legacy_err:
                raise RuntimeError(
                    f"{model_file} is neither a loadable StableHLO "
                    f"artifact ({stablehlo_err!r}) nor a parseable "
                    f"reference ProgramDesc ({legacy_err!r})"
                ) from legacy_err
            self._translated = None
            self._input_names = list(self._legacy.feed_names)
        self._inputs: Dict[str, PredictorTensor] = {}
        self._outputs: List[PredictorTensor] = []
        for n in self._input_names:
            self._inputs[n] = PredictorTensor(n)

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        import jax
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        args = [self._inputs[n]._value for n in self._input_names]
        if self._legacy is not None:
            out = self._legacy.run(dict(zip(self._input_names, args)))
        else:
            out = self._translated._exported.call(*args)
        leaves = jax.tree_util.tree_leaves(out)
        self._outputs = []
        for i, leaf in enumerate(leaves):
            t = PredictorTensor(f"output_{i}")
            t._value = leaf
            self._outputs.append(t)
        if inputs is not None:
            return [np.asarray(o._value) for o in self._outputs]
        return True

    def get_output_names(self):
        return [t.name for t in self._outputs] or ["output_0"]

    def get_output_handle(self, name):
        idx = int(name.rsplit("_", 1)[1])
        return self._outputs[idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
