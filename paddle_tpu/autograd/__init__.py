"""paddle.autograd namespace (reference `python/paddle/autograd/`)."""
from ..framework.autograd import backward, grad, is_grad_enabled, no_grad
from .functional import hessian, jacobian, jvp, vjp

__all__ = ["backward", "grad", "no_grad", "is_grad_enabled", "PyLayer",
           "PyLayerContext", "vjp", "jvp", "jacobian", "hessian"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op (reference `autograd/py_layer.py`): user defines
    static forward(ctx, *args) / backward(ctx, *grads); apply() records a
    TapeNode whose pullback calls the user backward."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework.autograd import TapeNode, is_grad_enabled
        from ..framework.tensor import Tensor
        ctx = PyLayerContext()
        out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)
        in_tensors = [a for a in args
                      if isinstance(a, Tensor) and not a.stop_gradient]
        if is_grad_enabled() and in_tensors:
            def vjp_fn(cots):
                cots = cots if isinstance(cots, tuple) else (cots,)
                grads = cls.backward(ctx, *[Tensor(c) for c in cots])
                grads = grads if isinstance(grads, (tuple, list)) else \
                    (grads,)
                return [g._value if isinstance(g, Tensor) else g
                        for g in grads]
            for t in outs:
                t.stop_gradient = False
            node = TapeNode(cls.__name__, vjp_fn, in_tensors, outs)
            for t in outs:
                t._node = node
        return out if single else tuple(outs)
