"""Functional higher-order autodiff (reference
`python/paddle/autograd/functional.py`: vjp/jvp/Jacobian/Hessian, the
incubate.autograd surface).

TPU-native: these are direct jax transforms over a functionalized view
of the user's Tensor→Tensor function — exact forward- and reverse-mode
derivatives, composable and jittable, where the reference double-walks
its tape."""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ..framework.autograd import trace_mode
from ..framework.tensor import Tensor

__all__ = ["vjp", "jvp", "jacobian", "hessian"]


def _wrap_fn(func: Callable) -> Callable:
    """Tensor-level func → pure array function (traced under trace_mode
    so framework ops lower instead of taping)."""
    def raw(*arrays):
        with trace_mode():
            outs = func(*[Tensor(a) for a in arrays])
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, outs,
            is_leaf=lambda t: isinstance(t, Tensor))
    return raw


def _unwrap(xs):
    seq = isinstance(xs, (list, tuple))
    items = list(xs) if seq else [xs]
    arrays = [x._value if isinstance(x, Tensor) else jnp.asarray(x)
              for x in items]
    return arrays, seq


def _wrap_out(tree):
    return jax.tree_util.tree_map(Tensor, tree)


def _check_no_create_graph(create_graph, name):
    if create_graph:
        raise NotImplementedError(
            f"{name}(create_graph=True): results are detached from the "
            f"eager tape; compose jax transforms (e.g. nest "
            f"jacobian/hessian calls) for higher-order graphs instead")


def vjp(func, xs, v=None):
    """reference `paddle.autograd.vjp`: (outputs, vjp_result). `v`
    defaults to ones like the output; when given it must mirror the
    output structure (its leaves are matched positionally)."""
    arrays, seq = _unwrap(xs)
    raw = _wrap_fn(func)
    out, pullback = jax.vjp(raw, *arrays)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot_arr, _ = _unwrap(v)
        treedef = jax.tree_util.tree_structure(out)
        cot = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(c) for c in cot_arr])
    grads = pullback(cot)
    grads = grads if seq else grads[0]
    return _wrap_out(out), _wrap_out(grads)


def jvp(func, xs, v=None):
    """reference `paddle.autograd.jvp`: forward-mode tangents."""
    arrays, _ = _unwrap(xs)
    raw = _wrap_fn(func)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        tan_arr, _ = _unwrap(v)
        tangents = tuple(tan_arr)
    out, tang = jax.jvp(raw, tuple(arrays), tangents)
    return _wrap_out(out), _wrap_out(tang)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """reference `paddle.autograd.jacobian` (batch=False semantics):
    d func(xs) / d xs, exact reverse-mode. allow_unused is moot here —
    an unused input yields exact zeros, never None."""
    _check_no_create_graph(create_graph, "jacobian")
    arrays, seq = _unwrap(xs)
    raw = _wrap_fn(func)
    jac = jax.jacrev(raw, argnums=tuple(range(len(arrays))))(*arrays)
    jac = jac if seq else (jac[0] if isinstance(jac, tuple) else jac)
    return _wrap_out(jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """reference `paddle.autograd.hessian`: d²(scalar func)/dxs², exact
    forward-over-reverse. func must return one scalar."""
    _check_no_create_graph(create_graph, "hessian")
    arrays, seq = _unwrap(xs)
    raw = _wrap_fn(func)

    def scalar(*a):
        out = raw(*a)
        leaves = jax.tree_util.tree_leaves(out)
        if len(leaves) != 1 or jnp.size(leaves[0]) != 1:
            raise ValueError(
                "hessian: func must return a single scalar "
                f"(got {len(leaves)} output(s), first of shape "
                f"{getattr(leaves[0], 'shape', None)})")
        return jnp.reshape(leaves[0], ())
    hes = jax.hessian(scalar, argnums=tuple(range(len(arrays))))(*arrays)
    if not seq:
        h = hes[0][0] if isinstance(hes, tuple) else hes
        return _wrap_out(h)
    return _wrap_out(hes)
