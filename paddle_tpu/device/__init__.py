"""paddle.device namespace (reference `python/paddle/device.py`)."""
from ..framework.place import (CPUPlace, CUDAPlace, TPUPlace, device_count,
                               get_device, is_compiled_with_cuda,
                               is_compiled_with_tpu, set_device)

__all__ = ["set_device", "get_device", "CPUPlace", "CUDAPlace", "TPUPlace",
           "device_count", "is_compiled_with_cuda", "is_compiled_with_tpu",
           "cuda"]


class cuda:
    """Parity shim: paddle.device.cuda.* maps to the accelerator."""

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        # XLA dataflow orders everything; an explicit fence:
        jax.effects_barrier() if hasattr(jax, "effects_barrier") else None

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        import jax
        try:
            stats = jax.local_devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        import jax
        try:
            stats = jax.local_devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0
