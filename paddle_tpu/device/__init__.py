"""paddle.device namespace (reference `python/paddle/device.py`).

Also owns the persistent XLA compilation-cache wiring: paddle_tpu points
`jax_compilation_cache_dir` at `FLAGS_xla_compilation_cache_dir`
(default `~/.cache/paddle_tpu/xla`) so a repeat run of the same model
skips XLA recompiles entirely — the first-step compile latency
`bench.py` reports drops to cache-read time. The wiring happens at
import when JAX_PLATFORMS names the backend, otherwise lazily at the
first framework compile (`maybe_enable_compilation_cache`) so importing
paddle_tpu never forces a JAX backend init. Opt out with the
`FLAGS_xla_compilation_cache=0` environment variable (always works); a
post-import `set_flags({"FLAGS_xla_compilation_cache": False})` is only
honored on the deferred first-compile branch — when JAX_PLATFORMS is
set, the flag is read during import itself.

The cache is NOT enabled on the CPU backend: XLA:CPU's serialized
executables drop input/output buffer aliasing, so a cache *hit* on a
donated train step reads freed buffers and silently corrupts numerics
(reproduced on jax 0.4.37 with the dp-sharded step — second process
reading the cache diverges to ~1e18). TPU executables round-trip
aliasing correctly; CPU callers who accept the risk can pass
`enable_compilation_cache(force=True)`, which now warns ONCE naming
that corruption class instead of overriding silently.

The same gate guards the serving program store
(`serving/program_store.py`, ISSUE 16): both policies call
`serialization_unsafe_backend()` here, so "is a deserialized
executable trustworthy on this backend" has exactly one answer — the
two refusals cannot drift apart. The store's root directory resolves
through `program_store_dir()` (FLAGS_gen_program_store_dir).
"""
import os as _os
import warnings as _warnings

from ..framework.place import (CPUPlace, CUDAPlace, TPUPlace, device_count,
                               get_device, is_compiled_with_cuda,
                               is_compiled_with_tpu, set_device)

__all__ = ["set_device", "get_device", "CPUPlace", "CUDAPlace", "TPUPlace",
           "device_count", "is_compiled_with_cuda", "is_compiled_with_tpu",
           "cuda", "enable_compilation_cache", "maybe_enable_compilation_cache",
           "compilation_cache_dir", "serialization_unsafe_backend",
           "warn_forced_serialization", "program_store_dir"]

_compile_cache_dir = None  # active dir once enable_compilation_cache ran
_cache_decision_pending = False  # JAX_PLATFORMS unset: decide at 1st compile
_force_warned = False  # one warning per process across BOTH policies


def _cpu_backend() -> bool:
    """True when jax will (or did) resolve to the CPU backend. Prefers the
    JAX_PLATFORMS env var (no backend init needed); falls back to asking
    jax, which initializes the default backend — only reached from the
    lazy first-compile path, never at import."""
    env = _os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if env:
        return env.split(",")[0].strip() == "cpu"
    try:
        import jax
        return jax.default_backend() == "cpu"
    except Exception:
        return True  # no backend at all — nothing to cache


def serialization_unsafe_backend() -> bool:
    """THE gate (ISSUE 16): True when executables deserialized on this
    backend cannot be trusted to keep input/output buffer aliasing —
    the PR 1 XLA:CPU corruption class, where a donated program read
    from a serialized artifact silently reads freed buffers. Both the
    persistent compilation cache (`enable_compilation_cache`) and the
    serving program store (`serving/program_store.py`) consult this
    single predicate, so the two refusal policies cannot drift."""
    return _cpu_backend()


def warn_forced_serialization(context: str) -> None:
    """One warning per process when a caller overrides the CPU gate
    (`force=True`) — names the PR 1 corruption class so the override
    is never silent. Shared by the compilation cache and the program
    store; whichever forces first emits it."""
    global _force_warned
    if _force_warned:
        return
    _force_warned = True
    _warnings.warn(
        f"{context}: forcing serialized-executable reuse on the CPU "
        f"backend. XLA:CPU deserialized executables have dropped "
        f"input/output donation aliasing on this stack (jax 0.4.37, "
        f"the PR 1 corruption class: a donated program silently reads "
        f"freed buffers and diverges ~1e18); every load therefore "
        f"runs the donation-aliasing self-check and a numeric smoke "
        f"probe, and falls back to live compile on any mismatch.",
        RuntimeWarning, stacklevel=3)


def enable_compilation_cache(path=None, force=False):
    """Point JAX's persistent compilation cache at `path` (defaults to
    FLAGS_xla_compilation_cache_dir). Returns the active directory, or
    None when the cache config is unsupported — or when the backend is
    CPU, where deserialized executables lose donation aliasing and give
    wrong results (see module docstring); `force=True` overrides, with
    a one-time warning naming that corruption class."""
    global _compile_cache_dir
    from ..framework.flags import flag
    if serialization_unsafe_backend():
        if not force:
            return None
        warn_forced_serialization("enable_compilation_cache(force=True)")
    d = _os.path.expanduser(path or flag("FLAGS_xla_compilation_cache_dir"))
    try:
        import jax
        _os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
    except Exception:
        return None
    _compile_cache_dir = d
    return d


def compilation_cache_dir():
    """Directory of the active persistent compile cache (None if off)."""
    return _compile_cache_dir


def program_store_dir():
    """Root directory configured for the serving program store
    (FLAGS_gen_program_store_dir, expanded; None when unset = store
    off). Resolution only — the CPU-soundness decision lives in
    `serialization_unsafe_backend()`, applied by the store itself."""
    from ..framework.flags import flag
    d = str(flag("FLAGS_gen_program_store_dir") or "").strip()
    return _os.path.expanduser(d) if d else None


def maybe_enable_compilation_cache():
    """Resolve a deferred cache decision (JAX_PLATFORMS unset at import).

    Idempotent and cheap after the first call. Invoked from the
    framework's compile entry points (Model train/eval/predict compile
    misses, bench.py) — at that moment a backend is about to be
    initialized anyway, so the CPU-soundness check in `_cpu_backend()`
    costs nothing extra, whereas running it at import would force
    backend init (TPU runtime grab / GPU preallocation) on every
    `import paddle_tpu`."""
    global _cache_decision_pending
    if not _cache_decision_pending:
        return
    _cache_decision_pending = False
    try:
        from ..framework.flags import flag
        # in this deferred branch the decision happens after import, so a
        # set_flags() opt-out CAN be honored — re-read the flag here
        if flag("FLAGS_xla_compilation_cache"):
            enable_compilation_cache()
    except Exception:
        pass


def _init_compilation_cache():
    global _cache_decision_pending
    from ..framework.flags import flag
    try:
        if not flag("FLAGS_xla_compilation_cache"):
            return
        if _os.environ.get("JAX_PLATFORMS", "").strip():
            enable_compilation_cache()  # env decides; no backend init
        else:
            _cache_decision_pending = True  # decide lazily at 1st compile
    except Exception:
        pass


_init_compilation_cache()


class cuda:
    """Parity shim: paddle.device.cuda.* maps to the accelerator."""

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        # XLA dataflow orders everything; an explicit fence:
        jax.effects_barrier() if hasattr(jax, "effects_barrier") else None

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        import jax
        try:
            stats = jax.local_devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        import jax
        try:
            stats = jax.local_devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0
