"""DataLoader (reference `fluid/reader.py:149` +
`fluid/dataloader/dataloader_iter.py:265/469`).

`num_workers>0` runs REAL worker processes (reference
`_DataLoaderIterMultiProcess`, `dataloader_iter.py:469`): each worker
fetches+collates to numpy and ships the batch through a POSIX
shared-memory RING (the reference's mmap'd `_shared_memory` allocator,
`fluid/memory/allocation/mmap_allocator.cc`): `num_workers *
prefetch_factor` reusable slots with explicit slot-free handoff. A worker
claims a free slot, writes the batch into its segment, and the parent
returns the slot once it copied the arrays out — so after the ring warms
up, steady state does ZERO shared-memory create/mmap/unlink syscalls (a
slot's segment is only recreated when a batch outgrows it). Metadata
rides a small mp.Queue; the parent copies each array once out of the
segment (JAX's CPU backend may alias numpy buffers, so live views over a
reusable slot would be clobbered by the next batch). Ordered hand-out,
worker-error propagation with the original traceback, sentinel + join
shutdown that sweeps exactly the fixed set of ring-slot names (not one
name per batch of the epoch).

`use_thread_workers=True` keeps the lighter in-process thread pool
(useful when the dataset is closure-heavy and cheap to decode). Batches
are handed out as framework Tensors (host-resident; H2D overlaps with
compute under jit).

Counters (framework/monitor.py, parent side): STAT_shm_slots_reused —
batches served from an already-mapped slot segment (steady state);
STAT_shm_slot_segments — parent-side segment (re)maps: ring size + any
regrows, constant across an arbitrarily long epoch.

Cross-process stat relay: a worker's STAT_ADDs land in its fork's
private registry copy, invisible to the trainer. Each worker therefore
zeroes its copy at start and ships `monitor.drain_deltas()` (counters +
raw histogram buckets, read-and-zero) alongside every result; the
parent `merge_deltas()`s them at hand-out. ANY stat a collate_fn or
dataset bumps in a worker — packing fill ratios, user counters,
histograms — appears in the parent's /metrics, exactly once.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import queue
import threading
import time
import traceback as _traceback
import uuid
from typing import Callable, Optional

import numpy as np

from ..framework.monitor import STAT_ADD
from ..framework.tensor import Tensor
from ..profiler import flight_recorder
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch):
    """Stack a list of samples (reference
    `fluid/dataloader/collate.py:default_collate_fn`)."""
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items))
                     for items in zip(*batch))
    return batch


def _to_tensors(collated):
    if isinstance(collated, np.ndarray):
        if collated.dtype == np.float64:
            collated = collated.astype(np.float32)
        return Tensor(collated)
    if isinstance(collated, dict):
        return {k: _to_tensors(v) for k, v in collated.items()}
    if isinstance(collated, (list, tuple)):
        return type(collated)(_to_tensors(v) for v in collated)
    return collated


# ---------------------------------------------------------------------------
# multiprocess workers with a reusable shared-memory slot ring
# ---------------------------------------------------------------------------

class _ArrRef:
    """Placeholder for an ndarray leaf stripped out of a collated batch."""
    __slots__ = ("idx",)

    def __init__(self, idx):
        self.idx = idx


# shutdown token pushed onto the free-slot queue so a worker blocked on a
# slot claim wakes up, drops its task and reaches the task sentinel
_RING_ABORT = -1


def _untrack(shm):
    """The PARENT owns every ring segment's lifetime (it unlinks them in
    shutdown); deregister from this process's resource_tracker so a
    worker's exit doesn't double-free a live slot."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def _slot_name(uid, slot):
    return f"{uid}r{slot}"


def _ring_claim(slot_q, cache, uid, needed):
    """Claim a free ring slot with capacity >= `needed` bytes.

    Returns (slot, gen, size, shm) or None on shutdown. The (gen, size)
    pair from the free queue is authoritative: gen bumps every time the
    slot's segment is recreated, so every process-local handle cache can
    tell a stale mapping from a live one. Steady state (cached handle,
    big-enough segment) touches no kernel object at all.
    """
    from multiprocessing import shared_memory
    slot, gen, size = slot_q.get()
    if slot == _RING_ABORT:
        return None
    cached = cache.pop(slot, None)
    if cached is not None and cached[0] != gen:
        try:
            cached[1].close()
        except Exception:
            pass
        cached = None
    if size < needed:
        # regrow: drop the current segment (if any) and recreate the same
        # name larger — the only syscalls after the ring has warmed up.
        # Unlink through a FRESH attach: its tracker register pairs with
        # unlink's unregister (cached handles were already deregistered)
        if cached is not None:
            try:
                cached[1].close()
            except Exception:
                pass
            cached = None
        if size > 0:
            try:
                old = shared_memory.SharedMemory(name=_slot_name(uid,
                                                                 slot))
                old.unlink()
                old.close()
            except FileNotFoundError:
                pass
            except Exception:
                pass
        shm = shared_memory.SharedMemory(name=_slot_name(uid, slot),
                                         create=True, size=needed)
        _untrack(shm)
        gen += 1
        size = needed
    elif cached is None:
        shm = shared_memory.SharedMemory(name=_slot_name(uid, slot))
        _untrack(shm)
    else:
        shm = cached[1]
    cache[slot] = (gen, shm)
    return slot, gen, size, shm


def _shm_encode_ring(obj, slot_q, cache, uid):
    """Strip ndarray leaves into a claimed ring slot.

    Returns (tree, slot, gen, size, specs) — slot None when the batch
    holds no arrays — or None when shutdown raced the claim. A failure
    AFTER the claim returns the slot before propagating, so the ring
    never loses capacity to a poisoned batch.
    """
    arrays = []

    def strip(x):
        if isinstance(x, np.ndarray):
            arrays.append(np.ascontiguousarray(x))
            return _ArrRef(len(arrays) - 1)
        if isinstance(x, dict):
            return {k: strip(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(strip(v) for v in x)
        return x

    tree = strip(obj)
    if not arrays:
        return tree, None, 0, 0, []
    total = sum(a.nbytes for a in arrays) or 1
    claim = _ring_claim(slot_q, cache, uid, total)
    if claim is None:
        return None
    slot, gen, size, shm = claim
    try:
        specs, off = [], 0
        for a in arrays:
            if a.nbytes:
                dst = np.ndarray(a.shape, a.dtype, buffer=shm.buf,
                                 offset=off)
                np.copyto(dst, a)
            specs.append((off, a.shape, a.dtype.str))
            off += a.nbytes
    except BaseException:
        slot_q.put((slot, gen, size))
        raise
    return tree, slot, gen, size, specs


def _shm_decode_ring(payload, slot_q, cache, uid):
    """Rebuild the batch from its ring slot and hand the slot back.

    Leaves are copied out (one memcpy per array): JAX's CPU backend may
    zero-copy alias a numpy buffer, and the slot's segment is reused by
    the next batch the moment it is freed. The expensive per-sample
    decode already happened in the worker; this single sequential memcpy
    is the transport cost. The parent's handle cache makes the steady
    state mmap-free (STAT_shm_slots_reused vs STAT_shm_slot_segments).
    """
    tree, slot, gen, size, specs = payload
    if slot is None:
        return tree
    from multiprocessing import shared_memory
    cached = cache.get(slot)
    if cached is None or cached[0] != gen:
        if cached is not None:
            try:
                cached[1].close()
            except Exception:
                pass
        shm = shared_memory.SharedMemory(name=_slot_name(uid, slot))
        _untrack(shm)  # the iterator's shutdown sweep owns the unlink
        cache[slot] = (gen, shm)
        STAT_ADD("STAT_shm_slot_segments")
    else:
        shm = cached[1]
        STAT_ADD("STAT_shm_slots_reused")
    try:
        arrays = [np.ndarray(shape, np.dtype(dt), buffer=shm.buf,
                             offset=off).copy()
                  for off, shape, dt in specs]
    finally:
        slot_q.put((slot, gen, size))

    def rebuild(x):
        if isinstance(x, _ArrRef):
            return arrays[x.idx]
        if isinstance(x, dict):
            return {k: rebuild(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(rebuild(v) for v in x)
        return x

    return rebuild(tree)


def _mp_worker_loop(dataset, collate_fn, worker_init_fn, wid, nw,
                    task_q, result_q, slot_q, use_shm, uid):
    """Target of one DataLoader worker process (numpy-only; never touches
    the accelerator)."""
    from ..framework import monitor
    _worker_info.info = WorkerInfo(wid, nw, dataset)
    # the fork inherited the parent's counter values; zero this process's
    # private registry copy so every shipped delta is purely work done
    # HERE — the generic cross-process stat relay (any STAT_*/histogram a
    # collate_fn or dataset touches in a worker reaches the trainer's
    # /metrics, not just the packing counters PR 6 special-cased)
    monitor.reset_all_stats()
    ring_cache = {}  # slot -> (gen, SharedMemory) — this worker's mappings
    rc = 0
    if worker_init_fn:
        try:
            worker_init_fn(wid)
        except Exception:
            result_q.put((-1, "err", _traceback.format_exc(), None))
            rc = 1
    while not rc:
        item = task_q.get()
        if item is None:
            break
        seq, indices = item
        try:
            out = collate_fn([dataset[i] for i in indices])
            if use_shm:
                payload = _shm_encode_ring(out, slot_q, ring_cache, uid)
                if payload is None:  # shutdown raced the slot claim
                    continue
            else:
                payload = (out, None, 0, 0, [])
            # drain-and-ship rides the result handoff: read-and-zero, so
            # each delta merges into the parent exactly once
            result_q.put((seq, "ok", payload, monitor.drain_deltas()))
        except Exception:
            result_q.put((seq, "err", _traceback.format_exc(),
                          monitor.drain_deltas()))
    for _, shm in ring_cache.values():
        try:
            shm.close()
        except Exception:
            pass
    result_q.close()
    result_q.join_thread()  # flush the feeder thread before hard exit
    os._exit(rc)            # skip atexit: the fork inherited jax/XLA state


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_thread_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.use_thread_workers = use_thread_workers
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._iterable_mode:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        if self.use_thread_workers:
            return self._iter_threaded()
        return self._iter_multiprocess()

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        STAT_ADD("STAT_dataloader_batches")
        return _to_tensors(self.collate_fn(samples))

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                STAT_ADD("STAT_dataloader_batches")
                yield _to_tensors(self.collate_fn(batch))
                batch = []
        if batch and not getattr(self, "drop_last", False):
            STAT_ADD("STAT_dataloader_batches")
            yield _to_tensors(self.collate_fn(batch))

    def _iter_multiprocess(self):
        """Real worker processes + shared-memory ring transport (reference
        `_DataLoaderIterMultiProcess`, `dataloader_iter.py:469`).

        The ring holds `num_workers * prefetch_factor` slots — exactly the
        prefetch window, so a worker always finds a free slot once the
        consumer keeps up, and the in-flight segment set is fixed-size:
        shutdown sweeps those names only, never one name per batch."""
        ctx = mp.get_context("fork" if "fork" in mp.get_all_start_methods()
                             else "spawn")
        flight_recorder.touch()  # crash context for worker-death raises
        nw = self.num_workers
        task_q = ctx.Queue()
        result_q = ctx.Queue()
        slot_q = ctx.Queue()
        use_shm = self.use_shared_memory
        # deterministic slot names ("<uid>r<slot>") let shutdown sweep the
        # whole ring even when a killed worker never reported its claim
        uid = f"ptpu{os.getpid()}x{uuid.uuid4().hex[:8]}"
        n_slots = max(1, nw * self.prefetch_factor)
        if use_shm:
            for slot in range(n_slots):
                slot_q.put((slot, 0, 0))  # gen 0, size 0: not yet created
        procs = [ctx.Process(
            target=_mp_worker_loop,
            args=(self.dataset, self.collate_fn, self.worker_init_fn,
                  wid, nw, task_q, result_q, slot_q, use_shm, uid),
            daemon=True) for wid in range(nw)]
        for p in procs:
            p.start()

        batches = list(self.batch_sampler)
        total = len(batches)
        depth = n_slots
        sent = 0
        for seq in range(min(depth, total)):
            task_q.put((seq, batches[seq]))
            sent += 1

        pending = {}
        ring_cache = {}  # slot -> (gen, SharedMemory): parent's mappings

        def shutdown():
            # drop queued-but-unstarted work so workers reach the sentinel
            # quickly even when the consumer abandoned the epoch early
            while True:
                try:
                    task_q.get_nowait()
                except Exception:
                    break
            if use_shm:
                # wake workers parked on a slot claim; they drop the task
                # and fall through to the sentinel
                for _ in procs:
                    try:
                        slot_q.put((_RING_ABORT, 0, 0))
                    except Exception:
                        pass
            for _ in procs:
                try:
                    task_q.put(None)
                except Exception:
                    pass
            for p in procs:
                p.join(timeout=30)
                if p.is_alive():
                    p.terminate()
            for _, shm in ring_cache.values():
                try:
                    shm.close()
                except Exception:
                    pass
            ring_cache.clear()
            pending.clear()
            if use_shm:
                # the whole in-flight set IS the ring: O(n_slots) names,
                # not O(total batches)
                from multiprocessing import shared_memory
                for slot in range(n_slots):
                    try:
                        leak = shared_memory.SharedMemory(
                            name=_slot_name(uid, slot))
                    except FileNotFoundError:
                        continue
                    except Exception:
                        break
                    try:
                        # the attach registered the name; unlink's own
                        # unregister pairs with it — no explicit untrack
                        leak.unlink()
                        leak.close()
                    except Exception:
                        pass

        try:
            # self.timeout follows the reference: 0 means wait forever;
            # liveness is polled so a dead worker still fails fast
            deadline = (time.monotonic() + self.timeout
                        if self.timeout else None)
            from ..framework import monitor
            for want in range(total):
                while want not in pending:
                    try:
                        seq, status, payload, deltas = result_q.get(
                            timeout=5)
                        if deltas:
                            # fold worker-side counters/histograms into
                            # THIS process's registry (error ships too:
                            # work done before the failure stays counted)
                            monitor.merge_deltas(deltas)
                    except queue.Empty:
                        dead = [p.pid for p in procs if not p.is_alive()]
                        if dead:
                            flight_recorder.dump(
                                "dataloader_worker_crash",
                                {"dead_pids": dead, "batch": want,
                                 "num_workers": nw, "sent": sent,
                                 "total": total})
                            raise RuntimeError(
                                f"DataLoader worker(s) {dead} died while "
                                f"batch {want} was outstanding") from None
                        if deadline and time.monotonic() > deadline:
                            flight_recorder.dump(
                                "dataloader_timeout",
                                {"timeout_s": self.timeout, "batch": want,
                                 "num_workers": nw})
                            raise RuntimeError(
                                f"DataLoader timed out after "
                                f"{self.timeout}s waiting for batch "
                                f"{want}") from None
                        continue
                    if status == "err":
                        flight_recorder.dump(
                            "dataloader_worker_error",
                            {"batch": int(seq), "num_workers": nw,
                             "error": payload.splitlines()[-1]
                             if payload else ""})
                        raise RuntimeError(
                            "DataLoader worker raised:\n" + payload)
                    pending[seq] = payload
                if sent < total:
                    task_q.put((sent, batches[sent]))
                    sent += 1
                deadline = (time.monotonic() + self.timeout
                            if self.timeout else None)
                STAT_ADD("STAT_dataloader_batches")
                decoded = _shm_decode_ring(pending.pop(want), slot_q,
                                           ring_cache, uid)
                yield _to_tensors(decoded)
        finally:
            shutdown()

    def _iter_threaded(self):
        """Ordered multi-thread prefetch (reference multiprocess iter
        `dataloader_iter.py:469`, re-designed without shared-mem plumbing)."""
        nw = self.num_workers
        depth = nw * self.prefetch_factor
        task_q: "queue.Queue" = queue.Queue(depth)
        done = object()
        results = {}
        results_lock = threading.Condition()
        stop = threading.Event()

        def worker(wid):
            _worker_info.info = WorkerInfo(wid, nw, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            while not stop.is_set():
                item = task_q.get()
                if item is done:
                    task_q.put(done)
                    return
                seq, indices = item
                try:
                    out = self._fetch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with results_lock:
                    results[seq] = out
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, args=(i,), daemon=True,
                                    name=f"paddle_tpu-loader-w{i}")
                   for i in range(nw)]
        for t in threads:
            t.start()

        def feeder():
            for seq, indices in enumerate(self.batch_sampler):
                if stop.is_set():
                    return
                task_q.put((seq, indices))
            task_q.put(done)

        feed_thread = threading.Thread(target=feeder, daemon=True)
        feed_thread.start()

        total = len(self.batch_sampler)
        try:
            for seq in range(total):
                with results_lock:
                    while seq not in results:
                        results_lock.wait(timeout=self.timeout or None)
                    out = results.pop(seq)
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            stop.set()
            try:
                task_q.put_nowait(done)
            except queue.Full:
                pass
